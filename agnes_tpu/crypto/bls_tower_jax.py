"""Fp2/Fp6/Fp12 extension tower for BLS12-381 in JAX (ISSUE 13).

The pairing's field stack on top of `bls_field_jax`'s 12-bit-limb
Barrett base field, under the same static trace-time value-bound (FV)
discipline — a formula change that would overflow fails the TRACE,
never a hardware run.  Tower (matching `bls_ref`'s FQ12, up to the
basis change below):

    Fp2  = Fp [u] / (u^2 + 1)                    FV2 (bls_field_jax)
    Fp6  = Fp2[v] / (v^3 - xi),  xi = 1 + u      three FV2 coeffs
    Fp12 = Fp6[w] / (w^2 - v)                    FV12: SIX FV2 coeffs
                                                 over {1, w, .., w^5}

`bls_ref.FQ12` carries 12 Fp coefficients over w with
w^12 = 2 w^6 - 2; with u = w^6 - 1 the two are the same field, and
the basis change is the linear map `pack_fq12`/`unpack_fq12` (host
side, exact).

Graph-size discipline (the tentpole's diet): every tower multiply
funnels ALL of its base-field products through ONE stacked
`fv_mul_pairs` call — an Fp12 Karatsuba multiply (3 Fp6 Karatsuba
multiplies = 18 Fp2 Karatsuba multiplies = 54 Fp products) costs a
single Barrett-reduce body in the traced graph, where per-call-site
instantiation would cost 54.  Karatsuba is chosen over schoolbook at
every level by RUNTIME product count (54 vs 108 for Fp12; the traced
op count is one stacked body either way — tests/test_bls_tower.py
pins the counts), and the cyclotomic square (Granger–Scott, for the
final exponentiation's hard part) costs 27 products in one body.

Frobenius constants gamma_i = xi^(i (p-1)/6) are python ints computed
at import (the `bls_ref` derive-and-assert pattern) and enter traces
as numpy limb constants.  Inversion exists at every level (the tests'
differential surface and the final exponentiation's easy part): Fp12
-> Fp6 -> Fp2 -> the Fermat chain `fv_inv`; all of them map 0 to 0,
so a degenerate pairing input collapses to a rejecting verdict, never
a crash.

Kernel lane (ISSUE 18): every stacked `fv_mul_pairs` body and
`fv_reduce_stack` carry chain this tower funnels through is exactly
what `crypto/pallas_field.py` fuses into single Pallas kernels — the
routing is the `bls_field_jax.field_backend` trace-time static set by
the registered BLS entries' `pallas_field=` knob, so this module is
backend-agnostic: same formulas, same FV bounds, either lane.

Oracle: `bls_ref` FQ2/FQ12 (tests/test_bls_tower.py)."""

from __future__ import annotations

from typing import List, NamedTuple, Tuple

import numpy as np

import jax.numpy as jnp

from agnes_tpu.crypto import bls_field_jax as BF
from agnes_tpu.crypto.bls_field_jax import (
    FV,
    FV2,
    NLIMBS,
    RED_BOUND,
    fv2_add,
    fv2_conj,
    fv2_mul_pairs_combine,
    fv2_mul_pairs_expand,
    fv2_neg,
    fv2_sub,
    fv_add,
    fv_in,
    fv_mul_pairs,
    fv_sub,
)
from agnes_tpu.crypto.bls_ref import P


class FV12(NamedTuple):
    """Fp12 element as six FV2 coefficients over {1, w, ..., w^5}."""

    c: Tuple[FV2, ...]


# --- host <-> device representation -----------------------------------------

def pack_fq12(e) -> np.ndarray:
    """bls_ref FQ12 -> [6, 2, NLIMBS] int32 limbs (host): with
    u = w^6 - 1, coefficient j over the Fp2 basis is
    (a_j + a_{j+6}) + a_{j+6} u."""
    out = np.zeros((6, 2, NLIMBS), np.int32)
    for j in range(6):
        out[j, 0] = BF.to_limbs((e.c[j] + e.c[j + 6]) % P)
        out[j, 1] = BF.to_limbs(e.c[j + 6] % P)
    return out


def unpack_fq12(arr) -> "object":
    """[..., 6, 2, NLIMBS] limbs (one element) -> bls_ref FQ12."""
    from agnes_tpu.crypto import bls_ref as ref

    a = np.asarray(arr)
    coeffs = [0] * 12
    for j in range(6):
        c0 = BF.from_limbs(a[..., j, 0, :]) % P
        c1 = BF.from_limbs(a[..., j, 1, :]) % P
        coeffs[j] = (c0 - c1) % P
        coeffs[j + 6] = c1
    return ref.FQ12(coeffs)


def fv12_in(arr: jnp.ndarray, bound: int = P) -> FV12:
    """[..., 6, 2, NLIMBS] -> FV12."""
    return FV12(tuple(
        FV2(FV(arr[..., j, 0, :], bound), FV(arr[..., j, 1, :], bound))
        for j in range(6)))


def fv12_out(x: FV12) -> jnp.ndarray:
    """FV12 -> [..., 6, 2, NLIMBS] limb array."""
    return jnp.stack([jnp.stack([c.c0.a, c.c1.a], axis=-2)
                      for c in x.c], axis=-3)


def fv12_one(shape: Tuple[int, ...] = ()) -> FV12:
    one = jnp.zeros(shape + (NLIMBS,), BF.I32).at[..., 0].set(1)
    zero = jnp.zeros(shape + (NLIMBS,), BF.I32)

    def cc(a):
        return FV2(FV(a, 1), FV(zero, 1))

    return FV12((cc(one),) + tuple(cc(zero) for _ in range(5)))


# --- small Fp2 helpers -------------------------------------------------------

def fv2_mul_pairs_expand_many(ops) -> List[tuple]:
    """Karatsuba operand pairs for a LIST of Fp2 products — the
    callers' collection step before one stacked `fv_mul_pairs`."""
    pairs: List[tuple] = []
    for x, y in ops:
        pairs.extend(fv2_mul_pairs_expand(x, y))
    return pairs


def fv2_mul_pairs_combine_many(prods: List[FV], n: int) -> List[FV2]:
    """Recombine the first 3n stacked products into n FV2 results."""
    return [fv2_mul_pairs_combine(*prods[3 * k:3 * k + 3])
            for k in range(n)]

def _mul_xi(t: FV2) -> FV2:
    """t * xi for xi = 1 + u: (c0 - c1) + (c0 + c1) u — adds only."""
    return FV2(fv_sub(t.c0, t.c1), fv_add(t.c0, t.c1))


def fv12_comps(x: FV12) -> List[FV]:
    """The 12 base-field components in THE canonical flattening
    order (c0.c0, c0.c1, c1.c0, ...) — every stacked-reduce /
    compare / restack path shares this one definition, so a
    coefficient-layout change (the ROADMAP Pallas rung) has a single
    place to happen."""
    out: List[FV] = []
    for c in x.c:
        out.extend([c.c0, c.c1])
    return out


def stack_fv2_comps(fvs: List[FV], off: int = 0,
                    n: int = 6) -> jnp.ndarray:
    """2n flattened components (fv12_comps order) -> one
    [..., n, 2, NLIMBS] limb array — the inverse restack (n=6 for an
    Fp12 element, n=3 for a projective G2 point)."""
    return jnp.stack(
        [jnp.stack([fvs[off + 2 * k].a, fvs[off + 2 * k + 1].a],
                   axis=-2) for k in range(n)], axis=-3)


def fv12_force_red(x: FV12) -> FV12:
    """All 12 base-field components below 4p in ONE stacked reduce —
    the loop-carry boundary's reduction (intermediates stay
    UNREDUCED: every multiply's stacked kernel auto-reduces grown
    operands itself, so per-component reductions between ops would
    only re-instantiate the Barrett body the diet exists to share)."""
    red = BF.fv_reduce_stack(fv12_comps(x))
    return FV12(tuple(FV2(red[2 * j], red[2 * j + 1])
                      for j in range(6)))


# --- Fp6 = Fp2[v]/(v^3 - xi), coefficients as FV2 triples --------------------
#
# Fp6 values travel as plain 3-tuples of FV2; FV12 groups its flat
# coefficients as d0 = (c0, c2, c4), d1 = (c1, c3, c5) with v = w^2.

def _fp6_mul_expand(x, y):
    """Karatsuba operand pairs of one Fp6 product (x, y: FV2 triples):
    6 Fp2 products = 18 Fp operand pairs, for a caller that stacks
    several Fp6 products into one `fv_mul_pairs` call."""
    a0, a1, a2 = x
    b0, b1, b2 = y
    fp2_ops = [
        (a0, b0), (a1, b1), (a2, b2),
        (fv2_add(a1, a2), fv2_add(b1, b2)),
        (fv2_add(a0, a1), fv2_add(b0, b1)),
        (fv2_add(a0, a2), fv2_add(b0, b2)),
    ]
    pairs: List[tuple] = []
    for fx, fy in fp2_ops:
        pairs.extend(fv2_mul_pairs_expand(fx, fy))
    return pairs


def _fp6_mul_combine(prods: List[FV]):
    """18 stacked Fp products -> the Fp6 result (Karatsuba
    recombination over v^3 = xi)."""
    f2 = [fv2_mul_pairs_combine(*prods[3 * k:3 * k + 3])
          for k in range(6)]
    v0, v1, v2, s12, s01, s02 = f2
    c0 = fv2_add(v0, _mul_xi(fv2_sub(s12, fv2_add(v1, v2))))
    c1 = fv2_add(fv2_sub(s01, fv2_add(v0, v1)), _mul_xi(v2))
    c2 = fv2_add(fv2_sub(s02, fv2_add(v0, v2)), v1)
    return (c0, c1, c2)


def _fp6_mul_expand_schoolbook(x, y):
    """Schoolbook alternative: 9 Fp2 products = 27 base pairs vs
    Karatsuba's 6/18.  NOT used by the tower — kept so the
    schoolbook-vs-Karatsuba choice stays MEASURED (tests pin both
    product counts and cross-check the two recombinations), not
    asserted from folklore."""
    pairs: List[tuple] = []
    for i in range(3):
        for j in range(3):
            pairs.extend(fv2_mul_pairs_expand(x[i], y[j]))
    return pairs


def _fp6_mul_combine_schoolbook(prods: List[FV]):
    f2 = fv2_mul_pairs_combine_many(prods, 9)
    acc = [None] * 5
    for i in range(3):
        for j in range(3):
            t = f2[3 * i + j]
            k = i + j
            acc[k] = t if acc[k] is None else fv2_add(acc[k], t)
    return (fv2_add(acc[0], _mul_xi(acc[3])),
            fv2_add(acc[1], _mul_xi(acc[4])),
            acc[2])


def _mul_v(x):
    """(a0, a1, a2) * v = (xi a2, a0, a1) over v^3 = xi."""
    a0, a1, a2 = x
    return (_mul_xi(a2), a0, a1)


def _fp6_add(x, y):
    return tuple(fv2_add(a, b) for a, b in zip(x, y))


def _fp6_sub(x, y):
    return tuple(fv2_sub(a, b) for a, b in zip(x, y))


# --- Fp12 arithmetic ---------------------------------------------------------

def _split(x: FV12):
    """Flat {w^i} coefficients -> (d0, d1) Fp6 pair over w^2 = v."""
    c = x.c
    return (c[0], c[2], c[4]), (c[1], c[3], c[5])


def _join(d0, d1) -> FV12:
    return FV12((d0[0], d1[0], d0[1], d1[1], d0[2], d1[2]))


def fv12_mul(x: FV12, y: FV12) -> FV12:
    """Karatsuba over Fp6 (t0 = d0 e0, t1 = d1 e1,
    t2 = (d0+d1)(e0+e1)): 54 base-field products, ALL of them through
    ONE stacked Barrett body (module docstring)."""
    d0, d1 = _split(x)
    e0, e1 = _split(y)
    pairs = (_fp6_mul_expand(d0, e0) + _fp6_mul_expand(d1, e1)
             + _fp6_mul_expand(_fp6_add(d0, d1), _fp6_add(e0, e1)))
    prods = fv_mul_pairs(pairs)
    t0 = _fp6_mul_combine(prods[0:18])
    t1 = _fp6_mul_combine(prods[18:36])
    t2 = _fp6_mul_combine(prods[36:54])
    r0 = _fp6_add(t0, _mul_v(t1))
    r1 = _fp6_sub(t2, _fp6_add(t0, t1))
    return _join(r0, r1)


def fv12_square(x: FV12) -> FV12:
    """x * x — shares `fv12_mul`'s one stacked body (the diet keeps
    the body count low; a dedicated squaring would trade one more
    traced body for ~25% fewer runtime products, the wrong side of
    the compile-budget trade here)."""
    return fv12_mul(x, x)


def fv12_conj(x: FV12) -> FV12:
    """The p^6-power Frobenius: c_i -> (-1)^i c_i.  On the
    cyclotomic subgroup (unitary elements) this IS the inverse."""
    return FV12(tuple(c if i % 2 == 0 else fv2_neg(c)
                      for i, c in enumerate(x.c)))


# Frobenius constants: gamma_i = xi^(i (p-1)/6) in Fp2, derived at
# import from the curve parameters (the bls_ref pattern) and asserted
# to be what the p-power Frobenius needs: w^p = gamma_1 * w.
def _fq2_pow(a: Tuple[int, int], e: int) -> Tuple[int, int]:
    out, b = (1, 0), a
    while e:
        if e & 1:
            out = ((out[0] * b[0] - out[1] * b[1]) % P,
                   (out[0] * b[1] + out[1] * b[0]) % P)
        b = ((b[0] * b[0] - b[1] * b[1]) % P, (2 * b[0] * b[1]) % P)
        e >>= 1
    return out


assert P % 6 == 1
_GAMMA: Tuple[Tuple[int, int], ...] = tuple(
    _fq2_pow((1, 1), i * (P - 1) // 6) for i in range(6))
#: numpy limb constants of gamma_1..gamma_5 (gamma_0 = 1 skipped)
_GAMMA_LIMBS = [
    (np.asarray(BF.to_limbs(g[0])), np.asarray(BF.to_limbs(g[1])))
    for g in _GAMMA]


def fv12_frob(x: FV12) -> FV12:
    """x^p: coefficient-wise Fp2 conjugation times the static
    gamma_i constants — 15 base-field products in one stacked body."""
    conj = [fv2_conj(c) for c in x.c]
    pairs: List[tuple] = []
    for i in range(1, 6):
        g0, g1 = _GAMMA_LIMBS[i]
        gc = FV2(fv_in(jnp.asarray(g0)), fv_in(jnp.asarray(g1)))
        pairs.extend(fv2_mul_pairs_expand(conj[i], gc))
    prods = fv_mul_pairs(pairs)
    out = [conj[0]]
    for k in range(5):
        out.append(fv2_mul_pairs_combine(*prods[3 * k:3 * k + 3]))
    return FV12(tuple(out))


def fv12_cyclotomic_square(x: FV12) -> FV12:
    """Granger–Scott squaring for UNITARY x (the final
    exponentiation's hard part lives in the cyclotomic subgroup):
    with Fp12 = Fp4[z]/(z^3 - s), z = w, s = w^3, and the Fp4
    components A = (c0, c3), B = (c1, c4), C = (c2, c5),

        x^2 = (3A^2 - 2A*) + (3 s C^2 + 2B*) z + (3B^2 - 2C*) z^2

    (* = Fp4 conjugation).  27 base-field products in one stacked
    body vs a full multiply's 54 — the hard part's dominant loop runs
    this body plus one multiply."""
    c = x.c
    groups = [(c[0], c[3]), (c[1], c[4]), (c[2], c[5])]
    pairs: List[tuple] = []
    for a, b in groups:
        # Fp4 square: (a + b s)^2 = (a^2 + xi b^2) + (2ab) s
        pairs.extend(fv2_mul_pairs_expand(a, a))
        pairs.extend(fv2_mul_pairs_expand(b, b))
        pairs.extend(fv2_mul_pairs_expand(a, b))
    prods = fv_mul_pairs(pairs)
    sqs = []
    for k in range(3):
        a2 = fv2_mul_pairs_combine(*prods[9 * k + 0:9 * k + 3])
        b2 = fv2_mul_pairs_combine(*prods[9 * k + 3:9 * k + 6])
        ab = fv2_mul_pairs_combine(*prods[9 * k + 6:9 * k + 9])
        sqs.append((fv2_add(a2, _mul_xi(b2)), fv2_add(ab, ab)))
    (A2, B2, C2) = sqs
    A, B, C = groups
    sC2 = (_mul_xi(C2[1]), C2[0])             # C^2 * s in Fp4

    def _3m2c(sq, orig):                      # 3*sq - 2*conj(orig)
        return (fv2_sub(fv2_add(fv2_add(sq[0], sq[0]), sq[0]),
                        fv2_add(orig[0], orig[0])),
                fv2_add(fv2_add(fv2_add(sq[1], sq[1]), sq[1]),
                        fv2_add(orig[1], orig[1])))

    def _3p2c(sq, orig):                      # 3*sq + 2*conj(orig)
        return (fv2_add(fv2_add(fv2_add(sq[0], sq[0]), sq[0]),
                        fv2_add(orig[0], orig[0])),
                fv2_sub(fv2_add(fv2_add(sq[1], sq[1]), sq[1]),
                        fv2_add(orig[1], orig[1])))

    ao = _3m2c(A2, A)
    bo = _3p2c(sC2, B)
    co = _3m2c(B2, C)
    return FV12((ao[0], bo[0], co[0], ao[1], bo[1], co[1]))


# --- inversion ---------------------------------------------------------------

def _fp6_inv(x):
    """Standard Fp6 inverse over v^3 = xi:
    t0 = a0^2 - xi a1 a2, t1 = xi a2^2 - a0 a1, t2 = a1^2 - a0 a2,
    norm = a0 t0 + xi a1 t2 + xi a2 t1; x^-1 = (t0, t1, t2)/norm."""
    a0, a1, a2 = x
    pairs = (fv2_mul_pairs_expand(a0, a0)
             + fv2_mul_pairs_expand(a1, a2)
             + fv2_mul_pairs_expand(a2, a2)
             + fv2_mul_pairs_expand(a0, a1)
             + fv2_mul_pairs_expand(a1, a1)
             + fv2_mul_pairs_expand(a0, a2))
    pr = fv_mul_pairs(pairs)
    sq = [fv2_mul_pairs_combine(*pr[3 * k:3 * k + 3])
          for k in range(6)]
    t0 = fv2_sub(sq[0], _mul_xi(sq[1]))
    t1 = fv2_sub(_mul_xi(sq[2]), sq[3])
    t2 = fv2_sub(sq[4], sq[5])
    pairs = (fv2_mul_pairs_expand(a0, t0)
             + fv2_mul_pairs_expand(a1, t2)
             + fv2_mul_pairs_expand(a2, t1))
    pr = fv_mul_pairs(pairs)
    n0 = fv2_mul_pairs_combine(*pr[0:3])
    n1 = fv2_mul_pairs_combine(*pr[3:6])
    n2 = fv2_mul_pairs_combine(*pr[6:9])
    ninv = BF.fv2_inv(fv2_add(n0, _mul_xi(fv2_add(n1, n2))))
    pairs = (fv2_mul_pairs_expand(t0, ninv)
             + fv2_mul_pairs_expand(t1, ninv)
             + fv2_mul_pairs_expand(t2, ninv))
    pr = fv_mul_pairs(pairs)
    return tuple(fv2_mul_pairs_combine(*pr[3 * k:3 * k + 3])
                 for k in range(3))


def fv12_inv(x: FV12) -> FV12:
    """(d0 + d1 w)^-1 = (d0 - d1 w) / (d0^2 - v d1^2): one Fp6
    inverse (one Fermat chain) + four Fp6 multiplies.  Used ONCE per
    pairing product (the easy part of the final exponentiation) and
    by the differential tests; maps 0 to 0."""
    d0, d1 = _split(x)
    pairs = _fp6_mul_expand(d0, d0) + _fp6_mul_expand(d1, d1)
    pr = fv_mul_pairs(pairs)
    d0sq = _fp6_mul_combine(pr[0:18])
    d1sq = _fp6_mul_combine(pr[18:36])
    t = _fp6_sub(d0sq, _mul_v(d1sq))
    tinv = _fp6_inv(t)
    pairs = _fp6_mul_expand(d0, tinv) + _fp6_mul_expand(d1, tinv)
    pr = fv_mul_pairs(pairs)
    r0 = _fp6_mul_combine(pr[0:18])
    r1 = tuple(fv2_neg(c) for c in _fp6_mul_combine(pr[18:36]))
    return _join(r0, r1)


# --- verdicts ----------------------------------------------------------------

def fv12_eq_one(x: FV12) -> jnp.ndarray:
    """x == 1 in Fp12 -> [...] bool: all 12 base-field components
    strict-reduced in ONE stacked reduce, then compared against the
    four < 4p representatives of their target residue."""
    comps = fv12_comps(x)
    stacked = jnp.stack([f.a for f in comps], axis=-2)
    bound = max(f.bound for f in comps)
    assert bound < BF.REDUCE_CAP
    strict = BF.reduce_cols(stacked, BF._ELEM_LIMB + BF.LMASK)
    ok = BF.strict_eq_mod_p(strict[..., 0, :], 1)
    for k in range(1, 12):
        ok = ok & BF.strict_eq_mod_p(strict[..., k, :], 0)
    return ok
