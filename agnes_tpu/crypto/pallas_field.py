"""Fused Pallas kernels for the 12-bit/33-limb Barrett field (ISSUE 18).

The BLS pairing and MSM entries bottom out in two `bls_field_jax`
bodies: `fv_mul_pairs` (stacked limb convolution + Barrett reduce) and
the `reduce_cols` carry chain (`fv_reduce_stack` / `fv_mul_small` /
`fv_strict`).  Rolled JAX schedules those as generic elementwise soup
— ~100k traced primitives for the pairing entry and limb values that
round-trip HBM between every carry pass.  This module is the
hand-tiled answer in the `pallas_verify.py` mold:

  - **one `pallas_call` per body**: the whole multiply -> loosen ->
    Barrett quotient -> subtract -> sequential carry chain runs inside
    a single kernel, limbs VMEM-resident throughout instead of one XLA
    op per carry pass;
  - **vreg-plane layout**: elements are [33, BH, 128] int32 blocks
    with the flattened batch on the (sublane, lane) axes — every limb
    is a whole 8x128 vreg, so a shifted multiply-add step is one vreg
    multiply-add (the verify-v2 layout lesson);
  - **static bound discipline preserved**: the kernels are
    parametrized by the STATIC carry-pass count derived from the
    caller's `FV` column bound (`_passes_needed`), so the trace-time
    bound proofs of `bls_field_jax` hold bit-for-bit at the kernel
    boundary — the interpret-mode differential asserts leaf-for-leaf
    limb equality against the rolled path, not just mod-p equality.

Backend selection lives in `bls_field_jax.field_backend` (trace-time
static; see its docstring): `False` keeps the rolled path, `True`
compiles the kernels (TPU), `"interpret"` runs them through the
Pallas interpreter (CPU differentials).  The registered entries carry
`pallas_backends=("tpu", "interpret")` — the per-backend lowering
record `agnes-lint --pass pallas` audits; "triton" stays unclaimed
until the GPU bench lane actually lowers these bodies (the kernel
bodies are plain jnp ops, but the claim must follow a real lowering,
not precede it).

Oracle: the rolled `bls_field_jax` path itself (exact limb equality);
see tests/test_pallas_field.py.
"""

from __future__ import annotations

import functools
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from agnes_tpu.crypto.bls_field_jax import (
    BITS,
    I32,
    LMASK,
    LOOSE,
    MU,
    MU_SHIFT_LIMBS,
    NLIMBS,
    _const_limbs,
    _ELEM_LIMB,
    _N65,
    _passes_needed,
)
from agnes_tpu.crypto.bls_ref import P

BH = 8                      # sublane rows per batch tile
TILE = BH * 128             # field elements per grid step

_MU_LIMBS = tuple(_const_limbs(MU))
_P_LIMBS = tuple(_const_limbs(P))

#: static carry-pass counts — the same `_passes_needed` arithmetic the
#: rolled `reduce_cols` runs, frozen here so the kernel bodies match
#: it limb-for-limb (the differential's exactness depends on it)
_MUL_PASSES = _passes_needed(NLIMBS * _ELEM_LIMB * _ELEM_LIMB)
_MU_PASSES = _passes_needed(len(_MU_LIMBS) * LOOSE * LMASK)
_P_PASSES = _passes_needed(len(_P_LIMBS) * LOOSE * LMASK)
_R_PASSES = _passes_needed(2 * LOOSE * LMASK)


# --- kernel-side limb ops (leading limb axis, [n, BH, 128] blocks) ----------


def _vp(r: jnp.ndarray) -> jnp.ndarray:
    """One exact vectorized carry pass along the leading limb axis —
    `bls_field_jax._vpass` transposed to the vreg-plane layout (top
    limb keeps its full value, signed carries via arithmetic shift)."""
    lo = r & LMASK
    hi = r >> BITS
    shift = jnp.concatenate([jnp.zeros_like(hi[:1]), hi[:-1]], axis=0)
    lo = jnp.concatenate([lo[:-1], r[-1:]], axis=0)
    return lo + shift


def _conv_const(a: jnp.ndarray, const: Tuple[int, ...],
                n_out: int) -> jnp.ndarray:
    """Limb convolution by a constant — the banded `a @ _MU_MAT` /
    `a @ _P_MAT` contractions as statically-shifted multiply-adds
    (constants INLINE: Pallas kernels must not capture arrays).
    out[k] = sum_i a[i] * const[k-i], rows beyond n_out dropped —
    exactly `_banded`'s i + j < n_out clipping."""
    n_in = a.shape[0]
    cols = None
    for j, cj in enumerate(const):
        if not cj:
            continue
        term = cj * a
        if j + n_in > n_out:
            term = term[:n_out - j]
        t = jnp.pad(term, [(j, n_out - j - term.shape[0])]
                    + [(0, 0)] * (term.ndim - 1))
        cols = t if cols is None else cols + t
    return cols


def _chain_strict_rows(r: jnp.ndarray) -> jnp.ndarray:
    """`bls_field_jax._chain_strict` on the leading limb axis:
    sequential signed carry chain over 24-bit limb PAIRS, emitting the
    interleaved lo/hi strict limbs row by row (no scatter — Mosaic has
    none; stacking rows is the `_freeze` precedent)."""
    n = r.shape[0]
    if n % 2:
        r = jnp.pad(r, [(0, 1)] + [(0, 0)] * (r.ndim - 1))
        n += 1
    s = [r[2 * k] + (r[2 * k + 1] << BITS) for k in range(n // 2)]
    c = jnp.zeros_like(s[0])
    mask24 = (1 << (2 * BITS)) - 1
    outs = []
    for k in range(n // 2):
        t = s[k] + c
        v = t & mask24
        outs.append(v & LMASK)
        outs.append(v >> BITS)
        c = t >> (2 * BITS)
    return jnp.stack(outs, axis=0)


def _reduce_body(x: jnp.ndarray, passes: int) -> jnp.ndarray:
    """Barrett reduction, fused: `reduce_cols` with every carry pass,
    both constant convolutions and the tail chain VMEM-resident.
    `passes` is the static `_passes_needed(col_bound)` of the caller's
    column bound — the FV bound contract at the kernel boundary."""
    for _ in range(passes):
        x = _vp(x)
    n = x.shape[0]
    if n < _N65:
        x = jnp.pad(x, [(0, _N65 - n)] + [(0, 0)] * (x.ndim - 1))
    t = _conv_const(x, _MU_LIMBS, _N65 + len(_MU_LIMBS))
    for _ in range(_MU_PASSES):
        t = _vp(t)
    q = t[MU_SHIFT_LIMBS:MU_SHIFT_LIMBS + NLIMBS]
    ql = _conv_const(q, _P_LIMBS, _N65)
    for _ in range(_P_PASSES):
        ql = _vp(ql)
    r = x - ql
    for _ in range(_R_PASSES):
        r = _vp(r)
    return _chain_strict_rows(r)[:NLIMBS]


def _mul_kernel(xa_ref, ya_ref, out_ref):
    """Fused `fv_mul_pairs` body: schoolbook limb convolution (33
    shifted multiply-adds, `_mul_cols` transposed) straight into the
    Barrett reduce — one kernel, zero HBM round-trips between them."""
    xa = xa_ref[:]
    ya = ya_ref[:]
    cols = None
    for i in range(NLIMBS):
        term = xa[i:i + 1] * ya
        t = jnp.pad(term, [(i, NLIMBS - 1 - i)]
                    + [(0, 0)] * (term.ndim - 1))
        cols = t if cols is None else cols + t
    out_ref[...] = _reduce_body(cols, _MUL_PASSES)


def _reduce_kernel(x_ref, out_ref, *, passes: int):
    """Fused `fv_reduce_stack` / carry-chain body."""
    out_ref[...] = _reduce_body(x_ref[:], passes)


# --- host/XLA wrappers ------------------------------------------------------


def _tile_rows(a: jnp.ndarray, r_pad: int) -> jnp.ndarray:
    """[R, NLIMBS] -> [NLIMBS, r_pad//128, 128] (zero-padded rows;
    zero elements reduce to zero, so padding is value-safe)."""
    a = jnp.pad(a, ((0, r_pad - a.shape[0]), (0, 0)))
    return jnp.moveaxis(a, -1, 0).reshape(NLIMBS, r_pad // 128, 128)


def _untile_rows(a: jnp.ndarray, r: int, lead) -> jnp.ndarray:
    return jnp.moveaxis(a.reshape(NLIMBS, -1), 0, -1)[:r].reshape(
        tuple(lead) + (NLIMBS,))


def _flatten(a: jnp.ndarray):
    lead = a.shape[:-1]
    r = int(np.prod(lead)) if lead else 1
    return a.reshape(r, a.shape[-1]), lead, r


def _specs(n_rows: int):
    spec = pl.BlockSpec((n_rows, BH, 128), lambda g: (0, g, 0),
                        memory_space=pltpu.VMEM)
    return spec


def mul_rows(xa: jnp.ndarray, ya: jnp.ndarray,
             interpret: bool = False) -> jnp.ndarray:
    """Fused multiply+reduce over [..., NLIMBS] limb arrays (matching
    leading shapes) -> [..., NLIMBS] strict limbs of a < 4p
    representative — limb-for-limb what the rolled
    `reduce_cols(_mul_cols(xa, ya), NLIMBS * _ELEM_LIMB**2)` returns.
    The caller (`bls_field_jax.fv_mul_pairs`) has already enforced the
    Barrett precondition via the static FV bounds."""
    xr, lead, r = _flatten(xa)
    yr, _, _ = _flatten(ya)
    if r == 0:
        return jnp.zeros(tuple(lead) + (NLIMBS,), I32)
    r_pad = -(-r // TILE) * TILE
    spec = _specs(NLIMBS)
    out = pl.pallas_call(
        _mul_kernel,
        grid=(r_pad // TILE,),
        in_specs=[spec, spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((NLIMBS, r_pad // 128, 128), I32),
        interpret=interpret,
    )(_tile_rows(xr, r_pad), _tile_rows(yr, r_pad))
    return _untile_rows(out, r, lead)


def reduce_rows(cols: jnp.ndarray, col_bound: int,
                interpret: bool = False) -> jnp.ndarray:
    """Fused Barrett reduce + carry chain over [..., NLIMBS]
    NON-NEGATIVE columns (value < REDUCE_CAP) -> strict < 4p limbs —
    limb-for-limb `reduce_cols(cols, col_bound)`.  The static
    col_bound picks the carry-pass count at trace time, same as the
    rolled path."""
    xr, lead, r = _flatten(cols)
    if r == 0:
        return jnp.zeros(tuple(lead) + (NLIMBS,), I32)
    passes = _passes_needed(col_bound)
    r_pad = -(-r // TILE) * TILE
    spec = _specs(NLIMBS)
    out = pl.pallas_call(
        functools.partial(_reduce_kernel, passes=passes),
        grid=(r_pad // TILE,),
        in_specs=[spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((NLIMBS, r_pad // 128, 128), I32),
        interpret=interpret,
    )(_tile_rows(xr, r_pad))
    return _untile_rows(out, r, lead)


# --- registered standalone entries ------------------------------------------
#
# The serve lane reaches these kernels INSIDE the registered BLS
# entries (bls_aggregate / bls_pairing_product, via the
# `field_backend` static); the standalone jits below are the
# direct-dispatch seam for the kernel differentials, the bench micro
# A/B and the lowering-support audit.


@functools.partial(jax.jit, static_argnums=(2,))
def _mul_pairs_jit(xa, ya, interpret: bool = False):
    return mul_rows(xa, ya, interpret)


@functools.partial(jax.jit, static_argnums=(1, 2))
def _reduce_jit(cols, col_bound: int, interpret: bool = False):
    return reduce_rows(cols, col_bound, interpret)


def mul_pairs_call(xa, ya, interpret: bool = False):
    """Dispatch the standalone fused-mul entry.  Interpret-mode
    executables NEVER touch the persistent compile cache (the
    pallas_verify r4 post-mortem: XLA's cache writer segfaults
    intermittently serializing interpreter graphs)."""
    if interpret:
        from jax._src import compilation_cache as _cc

        prev = jax.config.jax_enable_compilation_cache
        jax.config.update("jax_enable_compilation_cache", False)
        _cc.reset_cache()
        try:
            return _mul_pairs_jit(xa, ya, True)
        finally:
            jax.config.update("jax_enable_compilation_cache", prev)
            _cc.reset_cache()
    return _mul_pairs_jit(xa, ya, False)


def reduce_call(cols, col_bound: int, interpret: bool = False):
    """Dispatch the standalone reduce entry (cache dance as above)."""
    if interpret:
        from jax._src import compilation_cache as _cc

        prev = jax.config.jax_enable_compilation_cache
        jax.config.update("jax_enable_compilation_cache", False)
        _cc.reset_cache()
        try:
            return _reduce_jit(cols, col_bound, True)
        finally:
            jax.config.update("jax_enable_compilation_cache", prev)
            _cc.reset_cache()
    return _reduce_jit(cols, col_bound, False)


from agnes_tpu.device import registry as _registry  # noqa: E402

_registry.register(_registry.EntrySpec(
    name="pallas_fv_mul_pairs", fn=_mul_pairs_jit, jit=_mul_pairs_jit,
    statics=("interpret",), hot=False,
    pallas_backends=("tpu", "interpret")))
_registry.register(_registry.EntrySpec(
    name="pallas_fv_reduce", fn=_reduce_jit, jit=_reduce_jit,
    statics=("col_bound", "interpret"), hot=False,
    pallas_backends=("tpu", "interpret")))
