"""Batched Ed25519 verification in JAX — the north-star data plane.

Verifies [8]([S]B - [k]A) == [8]R (cofactored; Q := [S]B + [k](-A))
for a whole batch of signatures at once:

  - curve arithmetic on `field_jax` 13-bit int32 limbs, extended
    twisted-Edwards coordinates with the complete unified addition law
    (a = -1 is square mod p, d is not, so the formula has no special
    cases — no data-dependent branches anywhere);
  - the double-scalar multiplication is one `lax.scan` over 260
    MSB-first bit pairs (Straus/Shamir: shared doubling, one table add
    from {identity, B, -A, B - A} per step — adding the identity is
    fine under the complete law, keeping the select branch-free);
  - k = SHA-512(R || A || M) via `sha512_jax`, reduced by
    `scalar_jax.barrett_reduce`;
  - R decompresses under the same canonical rules as A; the equality
    is projective after three doublings of each side (no inversion).

Checks applied per RFC 8032 §5.1.7: A and R decode to curve points,
S < L, and the COFACTORED group equation [8]([S]B - [k]A) == [8]R
(the framework-wide policy; rationale in ed25519_ref.verify).
Oracle: `ed25519_ref.verify`, pinned to the RFC vectors.

The reference engine verifies nothing (vote identity/signatures are
"notably absent", SURVEY.md §2.1); this kernel is the added surface
that BASELINE.json's >= 1M verifies/sec north star measures.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from agnes_tpu.crypto import ed25519_ref as ref
from agnes_tpu.crypto import field_jax as F
from agnes_tpu.crypto import scalar_jax as S
from agnes_tpu.crypto import sha512_jax as sha

I32 = F.I32

# --- curve constants as limb arrays ----------------------------------------
P = F.P
D_LIMBS = F.to_limbs(ref.D)
D2_LIMBS = F.to_limbs(2 * ref.D % P)
SQRT_M1_LIMBS = F.to_limbs(ref.SQRT_M1)
P_LIMBS = F.to_limbs(P)
_BX, _BY = ref.BASE[0], ref.BASE[1]
BX_LIMBS = F.to_limbs(_BX)
BY_LIMBS = F.to_limbs(_BY)
BT_LIMBS = F.to_limbs(_BX * _BY % P)


class Point(NamedTuple):
    """Extended homogeneous coordinates; each field [..., 20] limbs."""

    x: jnp.ndarray
    y: jnp.ndarray
    z: jnp.ndarray
    t: jnp.ndarray


# --- backend selection ------------------------------------------------------
# 'pallas' runs the sequential loops (Straus, pow) as fused TPU kernels
# (crypto/pallas_ed25519.py) — the only way to the >= 1M verifies/sec
# north star; 'jnp' is the portable XLA path.  None = auto (pallas on
# TPU).  Set before the first verify_batch call in a process: the jit
# caches whatever backend was active at trace time.

_BACKEND: str | None = None
_INTERPRET = False


def set_backend(name: str | None, interpret: bool = False) -> None:
    """name in {'pallas', 'jnp', None=auto}; interpret=True runs the
    Pallas kernels in interpreter mode (CPU correctness tests)."""
    global _BACKEND, _INTERPRET
    assert name in (None, "pallas", "jnp")
    _BACKEND = name
    _INTERPRET = interpret


def _use_pallas() -> bool:
    if _BACKEND is not None:
        return _BACKEND == "pallas"
    return jax.default_backend() == "tpu"


def _pow(x: jnp.ndarray, e: int) -> jnp.ndarray:
    if _use_pallas():
        from agnes_tpu.crypto import pallas_ed25519 as pk
        return pk.pow_p_pallas(x, e, interpret=_INTERPRET)
    return F.pow_p(x, e)


def identity(shape: Tuple[int, ...]) -> Point:
    zero = jnp.zeros(shape + (F.NLIMBS,), I32)
    one = zero.at[..., 0].set(1)
    return Point(zero, one, one, zero)


def base_point(shape: Tuple[int, ...]) -> Point:
    return Point(
        jnp.broadcast_to(BX_LIMBS, shape + (F.NLIMBS,)),
        jnp.broadcast_to(BY_LIMBS, shape + (F.NLIMBS,)),
        identity(shape).y,
        jnp.broadcast_to(BT_LIMBS, shape + (F.NLIMBS,)),
    )


def point_add(p: Point, q: Point) -> Point:
    """Unified a=-1 twisted Edwards addition (complete; 9 muls)."""
    a = F.mul(F.sub(p.y, p.x), F.sub(q.y, q.x))
    b = F.mul(F.add(p.y, p.x), F.add(q.y, q.x))
    c = F.mul(F.mul(p.t, q.t), jnp.broadcast_to(D2_LIMBS, p.t.shape))
    d = F.carry(2 * F.mul(p.z, q.z))
    e, f = F.sub(b, a), F.sub(d, c)
    g, h = F.add(d, c), F.add(b, a)
    return Point(F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def point_neg(p: Point) -> Point:
    zero = jnp.zeros_like(p.x)
    return Point(F.sub(zero, p.x), p.y, p.z, F.sub(zero, p.t))


def point_equal(p: Point, q: Point) -> jnp.ndarray:
    """Projective equality: x1 z2 == x2 z1 and y1 z2 == y2 z1."""
    return (F.eq_mod_p(F.mul(p.x, q.z), F.mul(q.x, p.z))
            & F.eq_mod_p(F.mul(p.y, q.z), F.mul(q.y, p.z)))


def decompress(ybytes: jnp.ndarray) -> Tuple[Point, jnp.ndarray]:
    """[..., 32] little-endian encoded points -> (Point, ok).

    ok is False for non-canonical y (>= p), non-residue x^2, or the
    x = 0 / sign = 1 combination; coordinates are garbage when not ok
    (callers fold `ok` into the validity verdict — branch-free)."""
    b = ybytes.astype(I32)
    sign = b[..., 31] >> 7
    b = b.at[..., 31].set(b[..., 31] & 0x7F)
    y = F.bytes32_to_limbs(b)
    ok = ~F._geq(y, P_LIMBS)

    one = jnp.zeros_like(y).at[..., 0].set(1)
    y2 = F.sqr(y)
    u = F.sub(y2, one)
    v = F.add(F.mul(y2, jnp.broadcast_to(D_LIMBS, y.shape)), one)
    v3 = F.mul(v, F.sqr(v))
    v7 = F.mul(v3, F.mul(v3, v))
    x = F.mul(F.mul(u, v3), _pow(F.mul(u, v7), (P - 5) // 8))

    vx2 = F.mul(v, F.sqr(x))
    neg_u = F.sub(jnp.zeros_like(u), u)
    root_direct = F.eq_mod_p(vx2, u)
    root_flip = F.eq_mod_p(vx2, neg_u)
    x = jnp.where(root_flip[..., None],
                  F.mul(x, jnp.broadcast_to(SQRT_M1_LIMBS, x.shape)), x)
    ok &= root_direct | root_flip

    xf = F.freeze(x)
    x_is_zero = jnp.all(xf == 0, axis=-1)
    flip_sign = (xf[..., 0] & 1) != sign
    x = jnp.where(flip_sign[..., None], F.sub(jnp.zeros_like(xf), xf), xf)
    ok &= ~(x_is_zero & (sign == 1))
    return Point(x, y, one, F.mul(x, y)), ok


def compress(p: Point) -> jnp.ndarray:
    """Point -> [..., 32] canonical little-endian bytes (int32 0..255)."""
    zi = _pow(p.z, P - 2)
    x = F.freeze(F.mul(p.x, zi))
    y = F.freeze(F.mul(p.y, zi))
    out = F.limbs_to_bytes32(y)
    return out.at[..., 31].set(out[..., 31] | ((x[..., 0] & 1) << 7))


def straus_sub(s: jnp.ndarray, k: jnp.ndarray, a_point: Point) -> Point:
    """[s]B - [k]A by Shamir's trick: one scan over 260 shared-doubling
    steps, each adding one of {identity, B, -A, B-A} (branch-free
    4-way select; identity-adds are valid under the complete law)."""
    shape = s.shape[:-1]
    na = point_neg(a_point)
    b = base_point(shape)
    bma = point_add(b, na)
    idn = identity(shape)

    # stacked table [4, ..., 20] per coordinate, indexed by bs*1 + bk*2
    table = jax.tree.map(lambda *xs: jnp.stack(xs), idn, b, na, bma)
    sbits = S.bits_msb_first(s)          # [260, ...]
    kbits = S.bits_msb_first(k)

    def body(acc: Point, bits):
        bs, bk = bits
        sel = bs.astype(I32) + 2 * bk.astype(I32)     # [...]
        acc = point_add(acc, acc)
        onehot = (jnp.arange(4) == sel[..., None])    # [..., 4]
        pick = jax.tree.map(
            lambda tbl: jnp.sum(
                jnp.where(jnp.moveaxis(onehot, -1, 0)[..., None],
                          tbl, 0), axis=0),
            table)
        return point_add(acc, Point(*pick)), None

    acc, _ = jax.lax.scan(body, idn, (sbits, kbits))
    return acc


def verify_batch(pub: jnp.ndarray, sig: jnp.ndarray,
                 msg_blocks: jnp.ndarray) -> jnp.ndarray:
    """Batch verify.  pub [B, 32] bytes, sig [B, 64] bytes, msg_blocks
    [B, n_blocks, 32] uint32 — pre-padded SHA-512 blocks of
    R || A || M (see sha512_jax.pack_padded_host / the bridge packer).
    Returns [B] bool.

    COFACTORED semantics (framework-wide; rationale in
    ed25519_ref.verify): A and R must decode canonically, S < L, and
    [8]([S]B - [k]A) == [8]R — so this path, the Pallas kernel, the
    host verifiers and the MSM batch check agree on every input.

    On the Pallas backend this routes to the fused windowed-Straus
    verify kernel (crypto/pallas_verify.py) with signed 5-bit windows
    — measured faster than 4-bit on TPU v5e at every batch size
    (636k vs 618k/s at B=16k, 997k vs 932k/s at B=64k kernel-only;
    scripts/profile_verify.py r4); the jnp path below is the portable
    XLA implementation and differential oracle."""
    if _use_pallas():
        from agnes_tpu.crypto import pallas_verify as pv
        return pv.verify_batch_pallas(pub, sig, msg_blocks,
                                      interpret=_INTERPRET, window=5)
    a_point, ok_a = decompress(pub)
    r_point, ok_r = decompress(sig[..., :32])
    s = S.scalar_from_bytes32(sig[..., 32:])
    ok_s = S.is_canonical(s)
    k = S.barrett_reduce(S.digest_to_limbs(sha.sha512_blocks(msg_blocks)))
    q = straus_sub(s, k, a_point)
    for _ in range(3):                       # x8: kill the torsion
        q = point_add(q, q)
        r_point = point_add(r_point, r_point)
    return ok_a & ok_r & ok_s & point_equal(q, r_point)


verify_batch_jit = jax.jit(verify_batch)

from agnes_tpu.device import registry as _registry  # noqa: E402

_registry.register(_registry.EntrySpec(
    name="verify_batch", fn=verify_batch, jit=verify_batch_jit,
    hot=False))


def pack_verify_inputs_host(pubs, msgs, sigs):
    """Host packer for tests/benchmarks: lists of (32B pub, bytes msg,
    64B sig) -> (pub [B,32] i32, sig [B,64] i32, blocks [B,n,32] u32).
    All messages must have equal length (fixed-layout vote encoding,
    crypto.encoding)."""
    import numpy as np

    if not pubs:
        return (jnp.zeros((0, 32), I32), jnp.zeros((0, 64), I32),
                jnp.zeros((0, 1, 32), jnp.uint32))
    pub_arr = jnp.asarray(
        np.stack([np.frombuffer(p, np.uint8) for p in pubs]), I32)
    sig_arr = jnp.asarray(
        np.stack([np.frombuffer(sg, np.uint8) for sg in sigs]), I32)
    blocks = sha.pack_padded_host(
        [sg[:32] + p + m for p, m, sg in zip(pubs, msgs, sigs)])
    return pub_arr, sig_arr, blocks
