"""Batched SHA-512 in JAX for the Ed25519 challenge hash.

TPU has no 64-bit scalar unit worth leaning on, so every 64-bit SHA-512
word is carried as a (hi, lo) pair of uint32 lanes; the batch axis is
the vector axis.  The round/IV constants are *generated* at import time
from their definition (fractional parts of cube/square roots of the
first primes, FIPS 180-4 §4.2.3/§5.3.5) rather than typed in as a
table — the test suite pins the output against `hashlib.sha512`.

Only fixed-length single-block messages are needed by the vote path:
the canonical vote encoding is sized so that R(32) || A(32) || M(<=47)
fits one 128-byte padded block (a deliberate TPU-first design choice —
one compression per signature).  Multi-block inputs are handled by
looping compressions on the host-traced (static) block count.

The reference engine hashes nothing (SURVEY.md §5: no crypto anywhere);
this exists to serve the added signature surface.
"""

from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp

U32 = jnp.uint32
MASK32 = 0xFFFFFFFF

Word = Tuple[jnp.ndarray, jnp.ndarray]  # (hi, lo) uint32 lanes


# --- constant generation (FIPS 180-4: frac parts of prime roots) ------------

def _primes(n: int) -> List[int]:
    ps, x = [], 2
    while len(ps) < n:
        if all(x % p for p in ps):
            ps.append(x)
        x += 1
    return ps


def _icbrt(n: int) -> int:
    x = 1 << ((n.bit_length() + 2) // 3 + 1)
    while True:
        y = (2 * x + n // (x * x)) // 3
        if y >= x:
            break
        x = y
    return x


def _isqrt(n: int) -> int:
    import math
    return math.isqrt(n)


# K[t] = floor(frac(cbrt(prime_t)) * 2^64)
_K64 = [_icbrt(p << 192) & ((1 << 64) - 1) for p in _primes(80)]
# H0[i] = floor(frac(sqrt(prime_i)) * 2^64)
_H64 = [_isqrt(p << 128) & ((1 << 64) - 1) for p in _primes(8)]

K_HI = jnp.asarray([k >> 32 for k in _K64], U32)
K_LO = jnp.asarray([k & MASK32 for k in _K64], U32)
H0_HI = jnp.asarray([h >> 32 for h in _H64], U32)
H0_LO = jnp.asarray([h & MASK32 for h in _H64], U32)


# --- 64-bit word ops on (hi, lo) uint32 pairs -------------------------------

def _add(a: Word, *rest: Word) -> Word:
    hi, lo = a
    for bh, bl in rest:
        lo = lo + bl
        hi = hi + bh + (lo < bl).astype(U32)
    return hi, lo


def _xor(a: Word, b: Word) -> Word:
    return a[0] ^ b[0], a[1] ^ b[1]


def _and(a: Word, b: Word) -> Word:
    return a[0] & b[0], a[1] & b[1]


def _not(a: Word) -> Word:
    return ~a[0], ~a[1]


def _rotr(a: Word, n: int) -> Word:
    hi, lo = a
    if n >= 32:
        hi, lo, n = lo, hi, n - 32
    if n == 0:
        return hi, lo
    return ((hi >> n) | (lo << (32 - n)),
            (lo >> n) | (hi << (32 - n)))


def _shr(a: Word, n: int) -> Word:
    hi, lo = a
    if n >= 32:
        return jnp.zeros_like(hi), hi >> (n - 32)
    if n == 0:
        return hi, lo
    return hi >> n, (lo >> n) | (hi << (32 - n))


def _ch(e: Word, f: Word, g: Word) -> Word:
    return _xor(_and(e, f), _and(_not(e), g))


def _maj(a: Word, b: Word, c: Word) -> Word:
    return _xor(_xor(_and(a, b), _and(a, c)), _and(b, c))


def _big_sigma0(a: Word) -> Word:
    return _xor(_xor(_rotr(a, 28), _rotr(a, 34)), _rotr(a, 39))


def _big_sigma1(e: Word) -> Word:
    return _xor(_xor(_rotr(e, 14), _rotr(e, 18)), _rotr(e, 41))


def _sm_sigma0(w: Word) -> Word:
    return _xor(_xor(_rotr(w, 1), _rotr(w, 8)), _shr(w, 7))


def _sm_sigma1(w: Word) -> Word:
    return _xor(_xor(_rotr(w, 19), _rotr(w, 61)), _shr(w, 6))


def _compress(state: List[Word], block: jnp.ndarray) -> List[Word]:
    """One SHA-512 compression.  block: [..., 32] uint32 where columns
    (2t, 2t+1) are the (hi, lo) halves of big-endian message word t.

    Both the message schedule and the 80 rounds are `lax.scan`s: this
    XLA toolchain compiles at O(100) ops/sec, so an unrolled ~5k-op
    compression graph takes minutes to build while two small scan
    bodies compile in seconds."""
    # message schedule: scan a 16-word sliding window, emitting W[t]
    win_hi = jnp.stack([block[..., 2 * t] for t in range(16)], axis=0)
    win_lo = jnp.stack([block[..., 2 * t + 1] for t in range(16)], axis=0)

    def sched(win, _):
        wh, wl = win
        cur: Word = (wh[0], wl[0])
        nxt = _add(_sm_sigma1((wh[14], wl[14])), (wh[9], wl[9]),
                   _sm_sigma0((wh[1], wl[1])), (wh[0], wl[0]))
        wh = jnp.roll(wh, -1, axis=0).at[15].set(nxt[0])
        wl = jnp.roll(wl, -1, axis=0).at[15].set(nxt[1])
        return (wh, wl), cur

    _, (w_hi, w_lo) = jax.lax.scan(sched, (win_hi, win_lo), None, length=80)

    def round_fn(carry_state, wk):
        a, b, c, d, e, f, g, h = [(hi, lo) for hi, lo in
                                  zip(carry_state[0], carry_state[1])]
        whi, wlo, khi, klo = wk
        t1 = _add(h, _big_sigma1(e), _ch(e, f, g), (khi, klo), (whi, wlo))
        t2 = _add(_big_sigma0(a), _maj(a, b, c))
        h, g, f = g, f, e
        e = _add(d, t1)
        d, c, b = c, b, a
        a = _add(t1, t2)
        new = [a, b, c, d, e, f, g, h]
        return (tuple(x[0] for x in new), tuple(x[1] for x in new)), None

    init = (tuple(s[0] for s in state), tuple(s[1] for s in state))
    batch = block.shape[:-1]
    kshape = (80,) + (1,) * len(batch)
    kh = jnp.broadcast_to(K_HI.reshape(kshape), (80,) + batch)
    kl = jnp.broadcast_to(K_LO.reshape(kshape), (80,) + batch)
    (fh, fl), _ = jax.lax.scan(round_fn, init, (w_hi, w_lo, kh, kl))

    return [_add(s, (fh[i], fl[i])) for i, s in enumerate(state)]


def sha512_blocks(blocks: jnp.ndarray) -> jnp.ndarray:
    """SHA-512 over pre-padded message blocks.

    blocks: [..., n_blocks, 32] uint32 — each block is 16 big-endian
    64-bit words as (hi, lo) column pairs.  Returns the digest as
    [..., 16] uint32, same (hi, lo) big-endian word convention.
    The block count is static (python loop under jit)."""
    shape = blocks.shape[:-2]
    state: List[Word] = [
        (jnp.broadcast_to(H0_HI[i], shape), jnp.broadcast_to(H0_LO[i], shape))
        for i in range(8)]
    for blk in range(blocks.shape[-2]):
        state = _compress(state, blocks[..., blk, :])
    return jnp.stack([half for word in state for half in word], axis=-1)


def pad_message(msg_len: int) -> Tuple[int, int]:
    """(n_blocks, zero_bytes) of SHA-512 padding for a msg_len-byte
    message: 0x80, zeros, 16-byte big-endian bit length."""
    n_blocks = (msg_len + 1 + 16 + 127) // 128
    zeros = n_blocks * 128 - msg_len - 1 - 16
    return n_blocks, zeros


def pack_padded_host(msgs: "list[bytes]") -> jnp.ndarray:
    """Host-side packer: equal-length byte messages -> [B, n_blocks, 32]
    uint32 padded blocks for `sha512_blocks`.  The bridge's fixed-layout
    vote packer (device-side) mirrors this."""
    import numpy as np

    if not msgs:
        return jnp.zeros((0, 1, 32), U32)
    n = len(msgs[0])
    assert all(len(m) == n for m in msgs), "equal-length messages required"
    n_blocks, zeros = pad_message(n)
    out = np.zeros((len(msgs), n_blocks * 128), np.uint8)
    for i, m in enumerate(msgs):
        out[i, :n] = np.frombuffer(m, np.uint8)
        out[i, n] = 0x80
        bitlen = (8 * n).to_bytes(16, "big")
        out[i, -16:] = np.frombuffer(bitlen, np.uint8)
    words = out.reshape(len(msgs), n_blocks, 32, 4)
    packed = ((words[..., 0].astype(np.uint32) << 24)
              | (words[..., 1].astype(np.uint32) << 16)
              | (words[..., 2].astype(np.uint32) << 8)
              | words[..., 3].astype(np.uint32))
    return jnp.asarray(packed)


def digest_to_le_bytes_host(digest) -> bytes:
    """One [16] uint32 digest row -> the 64 raw bytes (as produced by
    hashlib .digest()), for host-side tests."""
    import numpy as np

    d = np.asarray(digest, np.uint64)
    words = [(int(d[2 * t]) << 32) | int(d[2 * t + 1]) for t in range(8)]
    return b"".join(w.to_bytes(8, "big") for w in words)
