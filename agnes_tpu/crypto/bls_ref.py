"""Pure-Python BLS12-381 — the aggregate-signature oracle.

The BLS lane's reference implementation, in the `ed25519_ref` oracle
style: plain Python ints, written from the curve's defining equations
(draft-irtf-cfrg-pairing-friendly-curves §4.2.1 parameterization), not
from any library.  Every derived constant (p, r, the cofactors) is
COMPUTED from the BLS parameter x at import and asserted against the
published hex values, so a transcription slip cannot silently ship.

Roles, mirroring PAPERS.md 2302.00418 (EdDSA vs BLS in committee-based
consensus):

* the **signer** the harness uses to fabricate BLS precommit shares
  (min-pubkey-size variant: pubkeys in G1 — 48-byte compressed —
  signatures in G2);
* the **pairing oracle** the serve plane's aggregate lane calls for
  its two O(1) pairings per vote class (`pairing_product_is_one`
  multiplies the Miller loops and pays ONE final exponentiation) —
  the O(N) aggregation work runs on device (`crypto/bls_jax.py`),
  only the O(1)-per-class check runs here;
* the **differential oracle** tests/test_bls.py pins the JAX limb
  field and MSM kernels against.

Hash-to-G2 is deterministic try-and-increment over SHA-256 with
cofactor clearing — internally consistent across every verifier in
this repo (the property consensus needs), NOT the IETF
hash_to_curve suite; this repo never interoperates with external BLS
stacks.  Rogue-key defense is proof-of-possession (`pop_prove` /
`pop_verify`, domain-separated hash): an aggregate is only sound over
keys whose holder proved knowledge of the secret — the serve lane's
key registry enforces it at admission (README "BLS aggregate lane"
has the threat model).

Not constant-time; host-side fixture/oracle use only.  The hot
aggregation path is the batched JAX kernel (`bls_jax`).
"""

from __future__ import annotations

import hashlib
from typing import List, Optional, Sequence, Tuple

# --- parameters, derived from the BLS parameter x and asserted --------------

X_PARAM = -0xD201000000010000                 # the BLS12-381 parameter
R = X_PARAM**4 - X_PARAM**2 + 1               # subgroup order (scalars)
P = (X_PARAM - 1) ** 2 * R // 3 + X_PARAM     # base field prime
H1 = (X_PARAM - 1) ** 2 // 3                  # G1 cofactor
H2 = (X_PARAM**8 - 4 * X_PARAM**7 + 5 * X_PARAM**6 - 4 * X_PARAM**4
      + 6 * X_PARAM**3 - 4 * X_PARAM**2 - 4 * X_PARAM + 13) // 9  # G2

assert P == int(
    "1a0111ea397fe69a4b1ba7b6434bacd764774b84f38512bf6730d2a0f6b0f624"
    "1eabfffeb153ffffb9feffffffffaaab", 16)
assert R == int(
    "73eda753299d7d483339d80809a1d80553bda402fffe5bfeffffffff00000001",
    16)
assert P % 4 == 3          # sqrt via a^((p+1)/4)

B_G1 = 4                   # E:  y^2 = x^3 + 4       over Fp
B_G2 = (4, 4)              # E': y^2 = x^3 + 4(u+1)  over Fp2, u^2 = -1


def _inv(x: int) -> int:
    return pow(x, P - 2, P)


def _sqrt_fp(x: int) -> Optional[int]:
    x %= P
    y = pow(x, (P + 1) // 4, P)
    return y if y * y % P == x else None


# --- Fp2 / Fp12 tower (py-polynomial fields, plain ints) --------------------
# Fp2 = Fp[u]/(u^2+1); Fp12 = Fp[w]/(w^12 - 2w^6 + 2), where w^6 = u+1.

class FQP:
    """Polynomial extension field element; subclasses fix degree and
    modulus coefficients (p(t) = t^deg + sum(mc[i] t^i))."""

    degree: int = 0
    mc: Tuple[int, ...] = ()

    __slots__ = ("c",)

    def __init__(self, coeffs: Sequence[int]):
        assert len(coeffs) == self.degree
        self.c = tuple(int(x) % P for x in coeffs)

    @classmethod
    def one(cls) -> "FQP":
        return cls((1,) + (0,) * (cls.degree - 1))

    @classmethod
    def zero(cls) -> "FQP":
        return cls((0,) * cls.degree)

    def __add__(self, o):
        return type(self)([a + b for a, b in zip(self.c, o.c)])

    def __sub__(self, o):
        return type(self)([a - b for a, b in zip(self.c, o.c)])

    def __neg__(self):
        return type(self)([-a for a in self.c])

    def __eq__(self, o):
        return type(self) is type(o) and self.c == o.c

    def __hash__(self):
        return hash((type(self).__name__, self.c))

    def scale(self, k: int) -> "FQP":
        return type(self)([a * k for a in self.c])

    def __mul__(self, o):
        d = self.degree
        buf = [0] * (2 * d - 1)
        for i, a in enumerate(self.c):
            if a:
                for j, b in enumerate(o.c):
                    buf[i + j] += a * b
        # reduce degree by the modulus polynomial, top down
        for k in range(2 * d - 2, d - 1, -1):
            top = buf[k]
            if top:
                buf[k] = 0
                for i, m in enumerate(self.mc):
                    if m:
                        buf[k - d + i] -= top * m
        return type(self)(buf[:d])

    def inv(self) -> "FQP":
        """Extended Euclid over Fp[t] against the modulus polynomial."""
        d = self.degree
        lm, hm = [1] + [0] * d, [0] * (d + 1)
        low = list(self.c) + [0]
        high = [m % P for m in self.mc] + [1]
        while _deg(low):
            r = _poly_div(high, low)
            r += [0] * (d + 1 - len(r))
            nm, new = list(hm), list(high)
            for i in range(d + 1):
                for j in range(d + 1 - i):
                    nm[i + j] -= lm[i] * r[j]
                    new[i + j] -= low[i] * r[j]
            nm = [x % P for x in nm]
            new = [x % P for x in new]
            lm, low, hm, high = nm, new, lm, low
        k = _inv(low[0])
        return type(self)([x * k for x in lm[:d]])

    def __truediv__(self, o):
        return self * o.inv()

    def __pow__(self, e: int):
        out = type(self).one()
        b = self
        while e:
            if e & 1:
                out = out * b
            b = b * b
            e >>= 1
        return out

    def is_zero(self) -> bool:
        return all(a == 0 for a in self.c)

    def __repr__(self):
        return f"{type(self).__name__}{self.c}"


def _deg(poly: List[int]) -> int:
    for i in range(len(poly) - 1, -1, -1):
        if poly[i]:
            return i
    return 0


def _poly_div(a: List[int], b: List[int]) -> List[int]:
    """Quotient of a/b over Fp[t] (b nonzero)."""
    da, db = _deg(a), _deg(b)
    out = [0] * (da - db + 1)
    rem = list(a)
    binv = _inv(b[db])
    for i in range(da - db, -1, -1):
        q = rem[db + i] * binv % P
        out[i] = q
        for j in range(db + 1):
            rem[i + j] -= q * b[j]
            rem[i + j] %= P
    return out


class FQ2(FQP):
    degree = 2
    mc = (1, 0)                       # u^2 = -1


class FQ12(FQP):
    degree = 12
    mc = (2, 0, 0, 0, 0, 0, -2, 0, 0, 0, 0, 0)   # w^12 = 2w^6 - 2


def fq2(a: int, b: int) -> FQ2:
    return FQ2((a, b))


def _sqrt_fq2(a: FQ2) -> Optional[FQ2]:
    """Square root in Fp2 (u^2 = -1) via the norm trick; None when `a`
    is a non-residue.  Verified by squaring before returning."""
    x, y = a.c
    if y == 0:
        s = _sqrt_fp(x)
        if s is not None:
            cand = fq2(s, 0)
        else:
            s = _sqrt_fp(-x % P)
            if s is None:
                return None
            cand = fq2(0, s)
        return cand if cand * cand == a else None
    n = (x * x + y * y) % P
    s = _sqrt_fp(n)
    if s is None:
        return None
    inv2 = _inv(2)
    lam = (x + s) * inv2 % P
    c = _sqrt_fp(lam)
    if c is None:
        lam = (x - s) * inv2 % P
        c = _sqrt_fp(lam)
        if c is None:
            return None
    d = y * _inv(2 * c % P) % P
    cand = fq2(c, d)
    return cand if cand * cand == a else None


# --- curve arithmetic (affine, field-generic) -------------------------------
# A point is (x, y) with field elements, or None for the identity.

def _is_fq(v) -> bool:
    return isinstance(v, int)


def _fadd(a, b):
    return (a + b) % P if _is_fq(a) else a + b


def _fsub(a, b):
    return (a - b) % P if _is_fq(a) else a - b


def _fmul(a, b):
    return a * b % P if _is_fq(a) else a * b


def _fdiv(a, b):
    return a * _inv(b) % P if _is_fq(a) else a / b


def _fsq(a):
    return _fmul(a, a)


def point_add(p1, p2):
    """Affine chord-tangent addition (field-generic; None = identity)."""
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if y1 == y2:
            if (y1 == 0 if _is_fq(y1) else y1.is_zero()):
                return None
            m = _fdiv(_fmul(3 if _is_fq(x1) else 3, _fsq(x1))
                      if _is_fq(x1) else _fsq(x1).scale(3),
                      _fmul(2, y1) if _is_fq(y1) else y1.scale(2))
        else:
            return None                     # P + (-P)
    else:
        m = _fdiv(_fsub(y2, y1), _fsub(x2, x1))
    x3 = _fsub(_fsub(_fsq(m), x1), x2)
    y3 = _fsub(_fmul(m, _fsub(x1, x3)), y1)
    return (x3, y3)


def point_neg(pt):
    if pt is None:
        return None
    x, y = pt
    return (x, (-y) % P if _is_fq(y) else -y)


def point_mul(k: int, pt):
    """Double-and-add scalar multiplication (MSB first)."""
    if k < 0:
        return point_mul(-k, point_neg(pt))
    q = None
    for bit in reversed(range(k.bit_length())):
        q = point_add(q, q)
        if (k >> bit) & 1:
            q = point_add(q, pt)
    return q


def on_curve_g1(pt) -> bool:
    if pt is None:
        return True
    x, y = pt
    return y * y % P == (x * x * x + B_G1) % P


def on_curve_g2(pt) -> bool:
    if pt is None:
        return True
    x, y = pt
    return y * y == x * x * x + FQ2(B_G2)


# generators (standard BLS12-381 generators, published coordinates)
G1 = (
    int("17f1d3a73197d7942695638c4fa9ac0fc3688c4f9774b905a14e3a3f171bac58"
        "6c55e83ff97a1aeffb3af00adb22c6bb", 16),
    int("08b3f481e3aaa0f1a09e30ed741d8ae4fcf5e095d5d00af600db18cb2c04b3ed"
        "d03cc744a2888ae40caa232946c5e7e1", 16),
)
G2 = (
    fq2(int("024aa2b2f08f0a91260805272dc51051c6e47ad4fa403b02b4510b647a"
            "e3d1770bac0326a805bbefd48056c8c121bdb8", 16),
        int("13e02b6052719f607dacd3a088274f65596bd0d09920b61ab5da61bbdc"
            "7f5049334cf11213945d57e5ac7d055d042b7e", 16)),
    fq2(int("0ce5d527727d6e118cc9cdc6da2e351aadfd9baa8cbdd3a76d429a6951"
            "60d12c923ac9cc3baca289e193548608b82801", 16),
        int("0606c4a02ea734cc32acd2b02bc28b99cb3e287e85a763af267492ab57"
            "2e99ab3f370d275cec1da1aaa9075ff05f79be", 16)),
)
assert on_curve_g1(G1) and on_curve_g2(G2)


# --- pairing (optimal ate, py-generic Miller loop) --------------------------

_ATE = -X_PARAM                       # positive Miller-loop count
_LOG_ATE = _ATE.bit_length() - 2      # loop from the bit below the MSB
W2_INV = FQ12((0,) * 2 + (1,) + (0,) * 9).inv()      # w^-2
W3_INV = FQ12((0,) * 3 + (1,) + (0,) * 8).inv()      # w^-3


def _cast_g1(pt):
    """G1 point -> E(Fp12) coordinates."""
    x, y = pt
    return (FQ12((x,) + (0,) * 11), FQ12((y,) + (0,) * 11))


def _twist(pt):
    """G2 (on the twist, Fp2 coords) -> E(Fp12): with v = w^6 the
    tower relation gives (v - 1)^2 = -1, so a + b*u embeds as
    (a - b) + b*w^6 and the twist constant 4(1 + u) embeds as 4*w^6;
    untwisting divides x by w^2 and y by w^3, landing on
    y^2 = x^3 + 4 over Fp12 (checked below)."""
    x, y = pt
    nx = FQ12((x.c[0] - x.c[1],) + (0,) * 5 + (x.c[1],) + (0,) * 5)
    ny = FQ12((y.c[0] - y.c[1],) + (0,) * 5 + (y.c[1],) + (0,) * 5)
    return (nx * W2_INV, ny * W3_INV)


# the twisted generator must land on E(Fp12): y^2 = x^3 + 4
_tx, _ty = _twist(G2)
assert _ty * _ty == _tx * _tx * _tx + FQ12((4,) + (0,) * 11)
del _tx, _ty


def _linefunc(p1, p2, t):
    """l_{p1,p2} evaluated at t (all in E(Fp12), affine, non-identity)."""
    x1, y1 = p1
    x2, y2 = p2
    xt, yt = t
    if x1 != x2:
        m = (y2 - y1) / (x2 - x1)
        return m * (xt - x1) - (yt - y1)
    if y1 == y2:
        m = (x1 * x1).scale(3) / y1.scale(2)
        return m * (xt - x1) - (yt - y1)
    return xt - x1


def miller_loop(q, p) -> FQ12:
    """Miller loop over the ate count (no final exponentiation); q, p
    in E(Fp12) affine coordinates."""
    if q is None or p is None:
        return FQ12.one()
    r = q
    f = FQ12.one()
    for i in range(_LOG_ATE, -1, -1):
        f = f * f * _linefunc(r, r, p)
        r = point_add(r, r)
        if _ATE & (1 << i):
            f = f * _linefunc(r, q, p)
            r = point_add(r, q)
    return f


_FE_EXP = (P**12 - 1) // R


def final_exponentiate(f: FQ12) -> FQ12:
    return f ** _FE_EXP


def pairing(q, p) -> FQ12:
    """e(p, q) for p in G1, q in G2 (bilinear, non-degenerate; the
    x < 0 conjugation is skipped — consistent across this repo, which
    never interoperates with external pairing stacks)."""
    return final_exponentiate(miller_loop(_twist(q), _cast_g1(p)))


def pairing_product_is_one(pairs) -> bool:
    """prod e(p_i, q_i) == 1 for [(G1 point, G2 point)] — ONE final
    exponentiation however many pairs, the O(1)-per-class check the
    serve lane's aggregate verify calls (two Miller loops + one final
    exp instead of two full pairings)."""
    f = FQ12.one()
    for p, q in pairs:
        if p is None or q is None:
            continue
        f = f * miller_loop(_twist(q), _cast_g1(p))
    return final_exponentiate(f) == FQ12.one()


# --- encodings --------------------------------------------------------------
# G1 pubkeys: 48-byte compressed big-endian x, ZCash-style flag bits in
# the top byte (compressed | infinity | y-sign).  G2 signatures travel
# UNCOMPRESSED on the wire (4 x 48-byte big-endian: x0 x1 y0 y1) so
# admission never pays an Fp2 square root per share.

_FLAG_C = 0x80
_FLAG_INF = 0x40
_FLAG_SIGN = 0x20


def _y_is_larger(y: int) -> bool:
    return y > P - y


def g1_compress(pt) -> bytes:
    if pt is None:
        return bytes([_FLAG_C | _FLAG_INF]) + bytes(47)
    x, y = pt
    flags = _FLAG_C | (_FLAG_SIGN if _y_is_larger(y) else 0)
    raw = bytearray(x.to_bytes(48, "big"))
    raw[0] |= flags
    return bytes(raw)


def g1_decompress(data: bytes):
    """48 bytes -> G1 point; raises ValueError on malformed input
    (wrong length, flags, x >= p, non-residue, off-subgroup)."""
    if len(data) != 48 or not data[0] & _FLAG_C:
        raise ValueError("bad G1 encoding")
    if data[0] & _FLAG_INF:
        if any(data[1:]) or data[0] & ~(_FLAG_C | _FLAG_INF):
            raise ValueError("bad G1 infinity encoding")
        return None
    x = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:], "big")
    if x >= P:
        raise ValueError("G1 x out of range")
    y = _sqrt_fp((x * x * x + B_G1) % P)
    if y is None:
        raise ValueError("G1 x not on curve")
    if _y_is_larger(y) != bool(data[0] & _FLAG_SIGN):
        y = P - y
    pt = (x, y)
    if point_mul(R, pt) is not None:
        raise ValueError("G1 point outside the r-torsion subgroup")
    return pt


SIG_BYTES = 192


def g2_to_bytes(pt) -> bytes:
    """G2 point -> 192 bytes (x0 x1 y0 y1, 48-byte big-endian each);
    the identity encodes as all-zero (not on the curve, so it is
    unambiguous)."""
    if pt is None:
        return bytes(SIG_BYTES)
    x, y = pt
    return (x.c[0].to_bytes(48, "big") + x.c[1].to_bytes(48, "big")
            + y.c[0].to_bytes(48, "big") + y.c[1].to_bytes(48, "big"))


def g2_from_bytes(data: bytes):
    """192 bytes -> G2 point (on-curve checked; subgroup NOT checked —
    the aggregate pairing check and the per-share fallback both fail a
    wrong-subgroup share, and a per-share r-torsion scalar mult at
    admission would cost more than the verify it guards; README
    documents the trade)."""
    if len(data) != SIG_BYTES:
        raise ValueError("bad G2 encoding length")
    if not any(data):
        return None
    vals = [int.from_bytes(data[i * 48:(i + 1) * 48], "big")
            for i in range(4)]
    if any(v >= P for v in vals):
        raise ValueError("G2 coordinate out of range")
    pt = (fq2(vals[0], vals[1]), fq2(vals[2], vals[3]))
    if not on_curve_g2(pt):
        raise ValueError("G2 point not on the twist curve")
    return pt


# --- hash to G2 -------------------------------------------------------------

_DST_MSG = b"AGNES-TPU-BLS12381G2-TAI-V1"
_DST_POP = b"AGNES-TPU-BLS12381G2-POP-V1"


def _fp_from_hash(dst: bytes, msg: bytes, tag: bytes, ctr: int) -> int:
    h = hashlib.sha512(dst + tag + ctr.to_bytes(4, "little") + msg)
    return int.from_bytes(h.digest(), "big") % P


def hash_to_g2(msg: bytes, dst: bytes = _DST_MSG):
    """Deterministic try-and-increment onto the twist, then cofactor-
    cleared into G2 (module docstring: internally consistent, not the
    IETF suite).  Never returns the identity for practical inputs (a
    counter whose candidate clears to infinity is skipped)."""
    ctr = 0
    while True:
        x = fq2(_fp_from_hash(dst, msg, b"x0", ctr),
                _fp_from_hash(dst, msg, b"x1", ctr))
        y = _sqrt_fq2(x * x * x + FQ2(B_G2))
        if y is not None:
            # deterministic sign choice: smaller (c0, c1) lexicographic
            if (y.c[0], y.c[1]) > ((-y).c[0], (-y).c[1]):
                y = -y
            pt = point_mul(H2, (x, y))
            if pt is not None:
                return pt
        ctr += 1


def hash_pop(pk_bytes: bytes):
    """The proof-of-possession message point: the pubkey hashed under
    its own domain tag, so a PoP can never double as a vote share."""
    return hash_to_g2(pk_bytes, dst=_DST_POP)


# --- the signature scheme (min-pubkey-size) ---------------------------------

def keygen(seed: bytes) -> Tuple[int, bytes]:
    """(sk scalar, 48-byte compressed G1 pubkey) from a seed."""
    if len(seed) < 16:
        raise ValueError("seed must be >= 16 bytes")
    sk = int.from_bytes(
        hashlib.sha512(b"AGNES-BLS-KEYGEN" + seed).digest(), "big") % R
    sk = sk or 1
    return sk, g1_compress(point_mul(sk, G1))


def sign(sk: int, msg: bytes) -> bytes:
    """192-byte uncompressed G2 signature [sk] H(msg)."""
    return g2_to_bytes(point_mul(sk, hash_to_g2(msg)))


def verify(pk_bytes: bytes, msg: bytes, sig_bytes: bytes) -> bool:
    """Single-share verification: e(g1, sig) == e(pk, H(msg)), as the
    one-final-exp product e(-g1, sig) * e(pk, H(msg)) == 1."""
    try:
        pk = g1_decompress(pk_bytes)
        sig = g2_from_bytes(sig_bytes)
    except ValueError:
        return False
    if pk is None or sig is None:
        return False
    return pairing_product_is_one(
        [(point_neg(G1), sig), (pk, hash_to_g2(msg))])


def verify_share(pk_pt, msg_point, sig_pt) -> bool:
    """verify() over already-decoded points and a precomputed message
    point — the serve lane's per-share FALLBACK check (one pairing
    product per share, message hash shared across the class)."""
    if pk_pt is None or sig_pt is None:
        return False
    return pairing_product_is_one(
        [(point_neg(G1), sig_pt), (pk_pt, msg_point)])


def aggregate_points(points) -> object:
    out = None
    for pt in points:
        out = point_add(out, pt)
    return out


def aggregate_verify_weighted(pk_points, weights: Sequence[int],
                              msg_point, agg_sig_pt) -> bool:
    """The per-class aggregate check: with apk = sum w_i * pk_i and
    asig = sum w_i * sig_i (the device MSM's outputs),

        e(g1, asig) == e(apk, H(class message))

    holds iff every weighted share signs the class message — weights
    are the validators' voting powers, so the ONE cleared lane carries
    the class's combined voting weight.  Checked as the one-final-exp
    product e(-g1, asig) * e(apk, H) == 1."""
    apk = None
    for pk, w in zip(pk_points, weights):
        apk = point_add(apk, point_mul(int(w), pk))
    if agg_sig_pt is None and apk is None:
        return True
    return pairing_product_is_one(
        [(point_neg(G1), agg_sig_pt), (apk, msg_point)])


# --- proof of possession ----------------------------------------------------

def pop_prove(sk: int, pk_bytes: bytes) -> bytes:
    """192-byte PoP: [sk] H_pop(pk) — proves knowledge of sk for pk,
    the rogue-key defense (README threat model)."""
    return g2_to_bytes(point_mul(sk, hash_pop(pk_bytes)))


def pop_verify(pk_bytes: bytes, pop_bytes: bytes) -> bool:
    try:
        pk = g1_decompress(pk_bytes)
        pop = g2_from_bytes(pop_bytes)
    except ValueError:
        return False
    if pk is None or pop is None:
        return False
    return pairing_product_is_one(
        [(point_neg(G1), pop), (pk, hash_pop(pk_bytes))])
