"""Canonical signing byte layouts — the single source of truth.

The reference carries no identity or signatures on votes (`Vote` lacks
height/validator/signature, reference lib.rs:23-27; SURVEY.md §2.1), so
this wire layout is new surface.  It is deliberately *fixed-width and
short*: a 45-byte vote message means R(32) || A(32) || M(45) = 109
bytes pads to exactly ONE SHA-512 block (limit 111), so batched
verification costs a single compression per signature
(sha512_jax.pad_message).

Every signer/verifier/packer must go through these functions — the
pure-Python signer (harness fixtures), the JAX batch verifier's host
packer, the C++ host core, and the device-side bridge packer all agree
on bytes by construction.

Layout (little-endian integers):

  vote:     type(1) | height(8) | round(4) | value(32)      = 45 bytes
  proposal: 0xP0(1) | height(8) | round(4) | pol_round(4)
            | value(32)                                     = 49 bytes
"""

from __future__ import annotations

VOTE_MSG_LEN = 45
PROPOSAL_MSG_LEN = 49
PROPOSAL_TAG = 0x50

# nil votes sign the all-ones value field.  Value ids are < 2^31
# (types.NIL_ID docs), so 2^256-1 can never collide with a real id —
# signing nil as 0 would be forgeable against value id 0.
NIL_WIRE = (1 << 256) - 1


def vote_signing_bytes(height: int, round: int, typ: int,
                       value: int | None) -> bytes:
    """Canonical 45-byte vote message (None value = nil -> all-ones)."""
    v = NIL_WIRE if value is None else int(value)
    return (bytes([int(typ)])
            + int(height).to_bytes(8, "little")
            + int(round).to_bytes(4, "little", signed=True)
            + v.to_bytes(32, "little"))


def proposal_signing_bytes(height: int, round: int, pol_round: int,
                           value: int) -> bytes:
    """Canonical 49-byte proposal message."""
    return (bytes([PROPOSAL_TAG])
            + int(height).to_bytes(8, "little")
            + int(round).to_bytes(4, "little", signed=True)
            + int(pol_round).to_bytes(4, "little", signed=True)
            + int(value).to_bytes(32, "little"))
