"""Pure-Python Ed25519 (RFC 8032) — the signing/verification oracle.

Written from the RFC 8032 specification (curve equations, encodings and
check equation as specified in §5.1; constants from §5.1 "edwards25519").
This is the reference implementation the C++ host verifier and the JAX
batched verifier are differential-tested against, and the signer the
harness uses to fabricate signed vote fixtures.  The reference engine
itself has no signature code anywhere (SURVEY.md §2.1: `Vote` carries no
signature; consensus_executor.rs:35-41 stubs "sign the vote").

Not constant-time; host-side fixture/oracle use only.  The hot
verification path is the batched JAX kernel (`ed25519_jax`).
"""

from __future__ import annotations

import hashlib
from typing import Tuple

# --- curve constants (RFC 8032 §5.1) ---------------------------------------
P = 2**255 - 19                      # field prime
L = 2**252 + 27742317777372353535851937790883648493   # group order
D = (-121665 * pow(121666, P - 2, P)) % P             # edwards d
SQRT_M1 = pow(2, (P - 1) // 4, P)                     # sqrt(-1)

# base point B (x from sign bit 0 with y = 4/5)
_BY = (4 * pow(5, P - 2, P)) % P


def _inv(x: int) -> int:
    return pow(x, P - 2, P)


def _sha512(data: bytes) -> bytes:
    return hashlib.sha512(data).digest()


def _sha512_int(data: bytes) -> int:
    return int.from_bytes(_sha512(data), "little")


# --- point arithmetic in extended homogeneous coordinates -------------------
# A point is (X, Y, Z, T) with x = X/Z, y = Y/Z, x*y = T/Z.

Point = Tuple[int, int, int, int]

IDENTITY: Point = (0, 1, 1, 0)


def _add(p: Point, q: Point) -> Point:
    """Unified addition on edwards25519 (complete formulas)."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % P
    b = (y1 + x1) * (y2 + x2) % P
    c = 2 * t1 * t2 * D % P
    d = 2 * z1 * z2 % P
    e, f, g, h = b - a, d - c, d + c, b + a
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def _double(p: Point) -> Point:
    return _add(p, p)


def _mul(s: int, p: Point) -> Point:
    """Scalar multiplication by double-and-add (MSB first)."""
    q = IDENTITY
    for bit in reversed(range(s.bit_length())):
        q = _double(q)
        if (s >> bit) & 1:
            q = _add(q, p)
    return q


def _recover_x(y: int, sign: int) -> int | None:
    """x with x^2 = (y^2-1)/(d*y^2+1), choosing the given sign bit."""
    if y >= P:
        return None
    x2 = (y * y - 1) * _inv(D * y * y + 1) % P
    if x2 == 0:
        if sign:
            return None
        return 0
    x = pow(x2, (P + 3) // 8, P)
    if (x * x - x2) % P != 0:
        x = x * SQRT_M1 % P
    if (x * x - x2) % P != 0:
        return None
    if (x & 1) != sign:
        x = P - x
    return x


BASE: Point = (_recover_x(_BY, 0), _BY, 1, (_recover_x(_BY, 0) * _BY) % P)


def _compress(p: Point) -> bytes:
    x, y, z, _ = p
    zi = _inv(z)
    x, y = x * zi % P, y * zi % P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def _decompress(data: bytes) -> Point | None:
    if len(data) != 32:
        return None
    enc = int.from_bytes(data, "little")
    y = enc & ((1 << 255) - 1)
    x = _recover_x(y, enc >> 255)
    if x is None:
        return None
    return (x, y, 1, x * y % P)


def point_equal(p: Point, q: Point) -> bool:
    x1, y1, z1, _ = p
    x2, y2, z2, _ = q
    return (x1 * z2 - x2 * z1) % P == 0 and (y1 * z2 - y2 * z1) % P == 0


# --- key generation, signing, verification (RFC 8032 §5.1.5-5.1.7) ---------

def _clamp(h: bytes) -> int:
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a


def keypair(seed: bytes) -> Tuple[bytes, bytes]:
    """(secret, public) from a 32-byte seed; secret is the seed itself."""
    if len(seed) != 32:
        raise ValueError("seed must be 32 bytes")
    a = _clamp(_sha512(seed))
    return seed, _compress(_mul(a, BASE))


def sign(secret: bytes, msg: bytes) -> bytes:
    """64-byte signature R || S."""
    h = _sha512(secret)
    a = _clamp(h)
    prefix = h[32:]
    pub = _compress(_mul(a, BASE))
    r = _sha512_int(prefix + msg) % L
    R = _compress(_mul(r, BASE))
    k = _sha512_int(R + pub + msg) % L
    s = (r + k * a) % L
    return R + s.to_bytes(32, "little")


def verify(public: bytes, msg: bytes, sig: bytes) -> bool:
    """COFACTORED check: [8]([S]B) == [8](R + [k]A), k = SHA-512(
    R || A || M) mod L, plus canonical encodings and S < L.

    The multiply-by-8 (vs RFC 8032's either-form allowance) is the
    framework's consensus-grade verification policy: it makes single,
    batched (msm_jax), and per-lane-kernel verification provably agree
    on every input — a signature's validity is a pure function of its
    bytes under every verification strategy, so nodes can never
    diverge on vote validity (the ZIP-215 agreement property).  All
    verifiers in this package (this oracle, the C++ host verifier,
    the jnp and Pallas batch verifiers, the MSM batch check) apply
    the same rule and are differential-tested for agreement."""
    if len(sig) != 64 or len(public) != 32:
        return False
    A = _decompress(public)
    if A is None:
        return False
    R = _decompress(sig[:32])
    if R is None:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= L:
        return False
    k = _sha512_int(sig[:32] + public + msg) % L
    return point_equal(_mul(8, _mul(s, BASE)),
                       _mul(8, _add(R, _mul(k, A))))
