"""Fused Ed25519 batch-verification Pallas kernel — verify v2.

One `pallas_call` per batch does the whole RFC 8032 §5.1.7 check:
decompress A and R (two square-root addition chains), build a 16-entry
window table of -A, run a 4-bit windowed joint double-scalar
multiplication [S]B - [k]A against a fixed-base multiples table of B,
and compare projectively against R.  No field inversion anywhere: the
old path compressed Q (one ~253-squaring inversion per batch element,
`ed25519_jax.compress`); here R itself is decompressed (a sqrt chain of
the same cost we already pay for A) and the equality check is
X_Q == x_R * Z_Q, Y_Q == y_R * Z_Q — saving a full pow stage and a
kernel launch.

Why it is fast (vs `pallas_ed25519.straus_sub_pallas`, the v1 kernel):

  - **vreg-plane layout**: field elements are [20, bh, 128] int32 with
    the *batch* on the (sublane, lane) axes — every limb is a whole
    8x128 vreg, so a schoolbook product step is one vreg multiply-add
    with zero sublane padding/rotation.  The v1 layout [20, B] put
    limbs on sublanes: 20 rows pad to 24 (17% waste) and every shifted
    add pays sublane rotations.  Measured per-signature field-mul cost
    drops ~2x.
  - **windowed Straus**: 65 windows x (4 doublings + 2 table adds)
    instead of 260 x (1 doubling + 1 add) — the add count falls 4x.
  - **true doubling formula** (dbl-2008-hwcd, a=-1): 4 squarings +
    3-4 muls, with a dedicated squaring (~60% of a mul) — the v1
    kernel doubled via the unified 9-mul addition.
  - **niels-form table adds**: 8 muls (extended table of -A) and
    6 muls (affine constant multiples of B; no Z mul, no T output).
  - **sqrt by addition chain**: 252 squarings + 11 muls, vs ~253
    squarings + ~125 muls of naive square-and-multiply.

Checks per RFC 8032 §5.1.7: A and R decode to curve points (canonical
y, residue x^2, x=0/sign=1 rejected), S < L (host/XLA side), and the
COFACTORED group equation [8]([S]B - [k]A) == [8]R — the framework's
consensus-grade policy (rationale: ed25519_ref.verify) under which
this kernel, the host verifiers and the MSM batch check agree on
every input.

Differential oracles: `ed25519_ref.verify` (RFC vectors) and the jnp
path `ed25519_jax.verify_batch` — see tests/test_pallas_verify.py.

The reference engine verifies nothing (vote signatures are "notably
absent" from its `Vote`, SURVEY.md §2.1; signing is stubbed at
/root/reference/src/consensus_executor.rs:35-41); this kernel is the
added data plane that BASELINE.json's >= 1M verifies/sec north star
measures.
"""

from __future__ import annotations

import functools
from typing import List, NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from agnes_tpu.crypto import ed25519_ref as ref
from agnes_tpu.crypto.field_jax import BITS, FOLD, LMASK, NLIMBS, P, I32

BH = 8                     # sublane rows per batch tile
TILE = BH * 128            # signatures per grid step
N_WIN = 65                 # 4-bit windows covering 260 bits
N_WIN5 = 52                # 5-bit signed windows covering 260 bits


def _const_limbs(x: int) -> List[int]:
    return [(x >> (BITS * i)) & LMASK for i in range(NLIMBS)]


_D = _const_limbs(ref.D)
_D2 = _const_limbs(2 * ref.D % P)
_SQRT_M1 = _const_limbs(ref.SQRT_M1)
_P_LIMBS = _const_limbs(P)

# 64p spread over the limbs (limb 19 oversized) — freeze offset, same
# as field_jax.SUB_K
_SUB_K = [LMASK] * NLIMBS
_SUB_K[0] = (1 << BITS) - 1216
_SUB_K[NLIMBS - 1] = (1 << 14) - 1
assert sum(k << (BITS * i) for i, k in enumerate(_SUB_K)) == 64 * P


# --- field ops on [20, ...batch] vreg-plane arrays --------------------------
# Same radix-2^13 signed-limb scheme as field_jax (see its docstring for
# the bound proofs); trailing dims are the batch tile.


def _add_const(a: jnp.ndarray, c: Sequence[int]) -> jnp.ndarray:
    """a + constant, limbwise scalar adds (no captured const arrays —
    Pallas kernels must build constants inline)."""
    return jnp.stack([a[k] + c[k] if c[k] else a[k]
                      for k in range(NLIMBS)], axis=0)


def _vp(r: jnp.ndarray, fold) -> jnp.ndarray:
    """One vectorized carry pass along the leading limb axis."""
    lo = r & LMASK
    hi = r >> BITS
    if fold is None:
        lo = jnp.concatenate([lo[:-1], r[-1:]], axis=0)
        shift = jnp.concatenate([jnp.zeros_like(hi[:1]), hi[:-1]], axis=0)
        return lo + shift
    shift = jnp.concatenate([hi[-1:] * fold, hi[:-1]], axis=0)
    return lo + shift


def _carry(r: jnp.ndarray, passes: int) -> jnp.ndarray:
    for _ in range(passes):
        r = _vp(r, FOLD)
    return r


def _fadd(a, b):
    return _carry(a + b, 2)


def _fsub(a, b):
    return _carry(a - b, 2)


def _mul_cols(cols: jnp.ndarray) -> jnp.ndarray:
    """[40, ...] raw schoolbook columns -> weak [20, ...] limbs.

    High half gets 2 exact passes (top row starts at 0, ends <= ~2^18,
    so FOLD*hi stays in int32); combined columns <= ~1.7e9 take 3
    folding passes to limb0 <= 8799, others <= 8196 — inside the weak
    |limb| <= ~9.4k envelope whose products stay under 2^31/20."""
    lo, hi = cols[:NLIMBS], cols[NLIMBS:]
    for _ in range(2):
        hi = _vp(hi, None)
    return _carry(lo + FOLD * hi, 3)


def _place(term: jnp.ndarray, i: int) -> jnp.ndarray:
    """Pad a [m, ...] row block to [40, ...] with rows at offset i."""
    pad = ([(i, 2 * NLIMBS - i - term.shape[0])]
           + [(0, 0)] * (term.ndim - 1))
    return jnp.pad(term, pad)


def _fmul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    cols = _place(a[0:1] * b, 0)
    for i in range(1, NLIMBS):
        cols = cols + _place(a[i:i + 1] * b, i)
    return _mul_cols(cols)


def _fsqr(a: jnp.ndarray) -> jnp.ndarray:
    """Squaring: halve the schoolbook via symmetry (a_i a_j + a_j a_i =
    (2a_i) a_j).  |2a_i| <= ~19k keeps column sums < 2^31."""
    a2 = a + a
    cols = _place(a[0:1] * a[0:1], 0)
    for i in range(1, NLIMBS):
        # diagonal term a_i^2 at column 2i
        cols = cols + _place(a[i:i + 1] * a[i:i + 1], 2 * i)
    for i in range(NLIMBS - 1):
        # off-diagonal 2 a_i a_j at columns i+j, j > i
        cols = cols + _place(a2[i:i + 1] * a[i + 1:], 2 * i + 1)
    return _mul_cols(cols)


def _fmul_const(a: jnp.ndarray, c: Sequence[int]) -> jnp.ndarray:
    cols = None
    for i, ci in enumerate(c):
        if ci:
            term = _place(ci * a, i)
            cols = term if cols is None else cols + term
    return _mul_cols(cols)


def _pow2k(x: jnp.ndarray, k: int) -> jnp.ndarray:
    """x^(2^k): k successive squarings (rolled loop for big k)."""
    if k <= 4:
        for _ in range(k):
            x = _fsqr(x)
        return x
    return jax.lax.fori_loop(0, k, lambda i, v: _fsqr(v), x)


def _sqrt_chain(z: jnp.ndarray) -> jnp.ndarray:
    """z^((p-5)/8) = z^(2^252 - 3): 252 squarings + 11 muls."""
    t0 = _fsqr(z)                       # 2
    t1 = _fmul(z, _pow2k(t0, 2))        # 9
    t0 = _fmul(t0, t1)                  # 11
    t0 = _fmul(t1, _fsqr(t0))           # 31 = 2^5 - 1
    t1 = _pow2k(t0, 5)
    t0 = _fmul(t1, t0)                  # 2^10 - 1
    t1 = _pow2k(t0, 10)
    t1 = _fmul(t1, t0)                  # 2^20 - 1
    t2 = _pow2k(t1, 20)
    t1 = _fmul(t2, t1)                  # 2^40 - 1
    t1 = _pow2k(t1, 10)
    t0 = _fmul(t1, t0)                  # 2^50 - 1
    t1 = _pow2k(t0, 50)
    t1 = _fmul(t1, t0)                  # 2^100 - 1
    t2 = _pow2k(t1, 100)
    t1 = _fmul(t2, t1)                  # 2^200 - 1
    t1 = _pow2k(t1, 50)
    t0 = _fmul(t1, t0)                  # 2^250 - 1
    return _fmul(_pow2k(t0, 2), z)      # 2^252 - 3


def _one(shape) -> jnp.ndarray:
    row = jax.lax.broadcasted_iota(I32, shape, 0)
    return jnp.where(row == 0, 1, 0).astype(I32)


def _chain_seq(r: jnp.ndarray):
    """Sequential signed carry chain over the limb axis."""
    c = jnp.zeros_like(r[0])
    outs = []
    for k in range(r.shape[0]):
        t = r[k] + c
        outs.append(t & LMASK)
        c = t >> BITS
    return jnp.stack(outs, axis=0), c


def _geq_const(a: jnp.ndarray, c: Sequence[int]) -> jnp.ndarray:
    """a >= c on strict limbs; returns [batch] bool."""
    gt = jnp.zeros(a.shape[1:], bool)
    eq = jnp.ones(a.shape[1:], bool)
    for k in reversed(range(NLIMBS)):
        gt = gt | (eq & (a[k] > c[k]))
        eq = eq & (a[k] == c[k])
    return gt | eq


def _sub_const(a: jnp.ndarray, c: Sequence[int]) -> jnp.ndarray:
    """a - c for a >= c, strict limbs in/out (sequential borrow)."""
    cy = jnp.zeros_like(a[0])
    outs = []
    for k in range(NLIMBS):
        t = a[k] - c[k] + cy
        outs.append(t & LMASK)
        cy = t >> BITS
    return jnp.stack(outs, axis=0)


def _freeze(a: jnp.ndarray) -> jnp.ndarray:
    """Canonical representative in [0, p): add 64p, exact-normalize,
    conditional-subtract ladder.  Mirrors field_jax.freeze."""
    r = _add_const(a, _SUB_K)
    for _ in range(3):
        r = _vp(r, FOLD)
    # limb-0 add via concat: Mosaic has no scatter-add, so .at[0].add
    # does not lower inside a TPU kernel
    r, c = _chain_seq(r)
    r = jnp.concatenate([r[0:1] + FOLD * c[None], r[1:]], axis=0)
    r, c2 = _chain_seq(r)
    r = jnp.concatenate([r[0:1] + FOLD * c2[None], r[1:]], axis=0)
    for m in (16, 8, 4, 2, 1, 1):
        mp = _const_limbs(m * P)
        ge = _geq_const(r, mp)
        r = jnp.where(ge[None], _sub_const(r, mp), r)
    return r


def _is_zero(a: jnp.ndarray) -> jnp.ndarray:
    f = _freeze(a)
    z = f[0] == 0
    for k in range(1, NLIMBS):
        z = z & (f[k] == 0)
    return z


def _where_fe(mask: jnp.ndarray, a, b):
    return jnp.where(mask[None], a, b)


# --- point ops (extended coords; each coord [20, ...batch]) -----------------


def _pt_dbl(X, Y, Z, want_t: bool):
    """dbl-2008-hwcd for a=-1: 4 squarings + 3 (4 with T) muls."""
    A = _fsqr(X)
    B = _fsqr(Y)
    ZZ = _fsqr(Z)
    C = ZZ + ZZ                              # raw; consumed by a sub
    E = _fsub(_fsub(_fsqr(_fadd(X, Y)), A), B)
    G = _fsub(B, A)
    F = _fsub(G, C)
    H = _carry(-(A + B), 2)
    X3 = _fmul(E, F)
    Y3 = _fmul(G, H)
    Z3 = _fmul(F, G)
    T3 = _fmul(E, H) if want_t else None
    return X3, Y3, Z3, T3


def _pt_add_niels(X1, Y1, Z1, T1, n_ypx, n_ymx, n_t2d, n_z2, want_t: bool):
    """extended + (projective-niels table entry): 8 muls (7 w/o T).
    Entry = (Y2+X2, Y2-X2, 2d*T2, 2*Z2); pass n_z2=None for affine
    entries (Z2=1 -> D = Z1+Z1, one mul fewer)."""
    A = _fmul(_fsub(Y1, X1), n_ymx)
    B = _fmul(_fadd(Y1, X1), n_ypx)
    C = _fmul(T1, n_t2d)
    D = _fmul(Z1, n_z2) if n_z2 is not None else _fadd(Z1, Z1)
    E = _fsub(B, A)
    F = _fsub(D, C)
    G = _fadd(D, C)
    H = _fadd(B, A)
    X3 = _fmul(E, F)
    Y3 = _fmul(G, H)
    Z3 = _fmul(F, G)
    T3 = _fmul(E, H) if want_t else None
    return X3, Y3, Z3, T3


def _pt_add_ext(p, q, want_t: bool):
    """Unified extended+extended addition (table build only)."""
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    A = _fmul(_fsub(Y1, X1), _fsub(Y2, X2))
    B = _fmul(_fadd(Y1, X1), _fadd(Y2, X2))
    C = _fmul_const(_fmul(T1, T2), _D2)
    ZZ = _fmul(Z1, Z2)
    D = ZZ + ZZ
    E = _fsub(B, A)
    F = _carry(D - C, 2)
    G = _carry(D + C, 2)
    H = _fadd(B, A)
    return (_fmul(E, F), _fmul(G, H), _fmul(F, G),
            _fmul(E, H) if want_t else None)


def _to_niels(p):
    """Extended point -> (Y+X, Y-X, 2d*T, 2Z) projective-niels entry."""
    X, Y, Z, T = p
    return (_fadd(Y, X), _fsub(Y, X), _fmul_const(T, _D2), _fadd(Z, Z))


def _decompress(y: jnp.ndarray, sign: jnp.ndarray):
    """Strict y limbs + sign -> (x limbs frozen, ok).  Mirrors
    ed25519_jax.decompress checks exactly."""
    shape = y.shape
    one = _one(shape)
    ok = ~_geq_const(y, _P_LIMBS)
    y2 = _fsqr(y)
    u = _fsub(y2, one)
    v = _carry(_fmul_const(y2, _D) + one, 2)
    v3 = _fmul(v, _fsqr(v))
    uv3 = _fmul(u, v3)
    uv7 = _fmul(uv3, _fmul(v3, v))
    x = _fmul(uv3, _sqrt_chain(uv7))
    vx2 = _fmul(v, _fsqr(x))
    root_direct = _is_zero(vx2 - u)
    root_flip = _is_zero(vx2 + u)
    x = _where_fe(root_flip, _fmul_const(x, _SQRT_M1), x)
    ok &= root_direct | root_flip
    xf = _freeze(x)
    x_is_zero = _is_zero(xf)
    flip = (xf[0] & 1) != sign
    x = _where_fe(flip, _fsub(jnp.zeros_like(xf), xf), xf)
    ok &= ~(x_is_zero & (sign == 1))
    return x, ok


def _select_tree(dig: jnp.ndarray, entries: list, nbits: int = 4):
    """Branch-free table pick: binary select tree over 2^nbits entries.
    entries: list of pytrees (tuples of [20,...] arrays or scalar limb
    lists), padded by the caller to 2^nbits; dig: [batch] int32 in
    [0, 2^nbits)."""
    bits = [(dig & (1 << b)) > 0 for b in range(nbits)]

    def sel(mask, t1, t0):
        return jax.tree.map(
            lambda a, b: jnp.where(
                mask[None] if hasattr(a, "ndim") and a.ndim > mask.ndim
                else mask, a, b), t1, t0)

    lvl = entries
    for b in range(nbits):
        if len(lvl) == 1:
            break
        lvl = [sel(bits[b], lvl[2 * i + 1], lvl[2 * i])
               for i in range(len(lvl) // 2)]
    return lvl[0]


# --- fixed-base multiples of B (host constants) -----------------------------


@functools.lru_cache(maxsize=None)
def _btable(n: int = 16) -> tuple:
    """((y+x), (y-x), 2dxy) affine-niels limb tuples for e*B,
    e = 0..n-1 (n=16 for the 4-bit kernel, 17 for signed 5-bit)."""
    out = []
    for e in range(n):
        if e == 0:
            x, y = 0, 1
        else:
            pt = ref._mul(e, ref.BASE)
            zi = ref._inv(pt[2])
            x, y = pt[0] * zi % P, pt[1] * zi % P
        out.append((tuple(_const_limbs((y + x) % P)),
                    tuple(_const_limbs((y - x) % P)),
                    tuple(_const_limbs(2 * ref.D * x * y % P))))
    return tuple(out)


# --- the kernel -------------------------------------------------------------


def _verify_kernel(ya_ref, sa_ref, yr_ref, sr_ref, sdig_ref, kdig_ref,
                   out_ref, *, signed5: bool = False):
    """The fused verify kernel body.  signed5=False: 65 4-bit unsigned
    windows over a 16-entry table.  signed5=True: 52 5-bit SIGNED
    windows (digits in [-16, 15]) over a 17-entry table — 13 fewer
    windows means 26 fewer table adds for the same 260 doublings, at
    the cost of one more table entry and a conditional negation
    (negating a niels entry is a swap of (Y+X, Y-X) plus -t2d: three
    selects, no field mul)."""
    shape = ya_ref.shape[1:]             # (BH, 128)
    one = _one((NLIMBS,) + tuple(shape))
    zero = jnp.zeros_like(one)

    # decompress A and R (two independent sqrt chains; good ILP)
    xa, ok_a = _decompress(ya_ref[:], sa_ref[:])
    xr, ok_r = _decompress(yr_ref[:], sr_ref[:])
    ya = ya_ref[:]
    yr = yr_ref[:]

    # -A extended (Z=1): negate x and t
    nax = _fsub(zero, xa)
    na = (nax, ya, one, _fmul(nax, ya))

    # table[e] = e * (-A) in projective-niels form
    n_ent = 17 if signed5 else 16
    ext = [None] * n_ent
    ext[1] = na
    ext[2] = _pt_dbl(*na[:3], want_t=True)
    for e in range(3, n_ent, 2):
        ext[e] = _pt_add_ext(ext[e - 2], ext[2], want_t=True)
    for e in range(4, n_ent, 2):
        p = ext[e // 2]
        ext[e] = _pt_dbl(p[0], p[1], p[2], want_t=True)
    id_niels = (one, one, zero, _fadd(one, one))
    atab = [id_niels] + [_to_niels(ext[e]) for e in range(1, n_ent)]
    btab = [tuple(list(c) for c in entry) for entry in _btable(n_ent)]

    def pick(e, tab):
        """Table pick for e in [0, n_ent).  signed5 keeps the CHEAP
        4-level tree over entries 0..15 and overlays the single extra
        entry 16 with one select — a 5-level tree over 32 padded
        entries would double the select count and eat the fewer-window
        savings."""
        sel = _select_tree(e & 15 if signed5 else e, tab[:16], 4)
        if not signed5:
            return sel
        is16 = e == 16

        def ov(top, lo):
            # top: entry-16 leaf (array, or python int for the B
            # table's scalar constants); lo: the tree-selected leaf
            arr = top if hasattr(top, "ndim") else lo
            mask = is16[None] if arr.ndim > is16.ndim else is16
            return jnp.where(mask, top, lo)

        return jax.tree.map(ov, tab[16], sel)

    dbls_per_win = 5 if signed5 else 4

    def body(i, acc):
        X, Y, Z = acc
        for j in range(dbls_per_win - 1):
            X, Y, Z, _ = _pt_dbl(X, Y, Z, want_t=False)
        X, Y, Z, T = _pt_dbl(X, Y, Z, want_t=True)
        kd = kdig_ref[i]
        sd = sdig_ref[i]
        if signed5:
            neg_k = kd < 0
            ek = jnp.where(neg_k, -kd, kd)
            neg_s = sd < 0
            es = jnp.where(neg_s, -sd, sd)
        else:
            ek, es = kd, sd
        n_ypx, n_ymx, n_t2d, n_z2 = pick(ek, atab)
        if signed5:
            # -(Y+X, Y-X, 2dT, 2Z) = (Y-X, Y+X, -2dT, 2Z)
            n_ypx, n_ymx = (_where_fe(neg_k, n_ymx, n_ypx),
                            _where_fe(neg_k, n_ypx, n_ymx))
            n_t2d = _where_fe(neg_k, _carry(-n_t2d, 2), n_t2d)
        X, Y, Z, T = _pt_add_niels(X, Y, Z, T, n_ypx, n_ymx, n_t2d, n_z2,
                                   want_t=True)
        b_ypx, b_ymx, b_t2d = pick(es, btab)
        b_ypx = jnp.stack(list(b_ypx), axis=0)
        b_ymx = jnp.stack(list(b_ymx), axis=0)
        b_t2d = jnp.stack(list(b_t2d), axis=0)
        if signed5:
            b_ypx, b_ymx = (_where_fe(neg_s, b_ymx, b_ypx),
                            _where_fe(neg_s, b_ypx, b_ymx))
            b_t2d = _where_fe(neg_s, _carry(-b_t2d, 2), b_t2d)
        X, Y, Z, _ = _pt_add_niels(X, Y, Z, T, b_ypx, b_ymx, b_t2d, None,
                                   want_t=False)
        return X, Y, Z

    X, Y, Z = jax.lax.fori_loop(
        0, N_WIN5 if signed5 else N_WIN, body, (zero, one, one))

    # COFACTORED equality (framework-wide policy; see
    # ed25519_ref.verify): [8]Q == [8]R so single/batch/MSM
    # verification agree on every input.  Three doublings each side,
    # then projective cross-multiplied equality.
    RX, RY, RZ = xr, yr, one
    for _ in range(3):
        X, Y, Z, _ = _pt_dbl(X, Y, Z, want_t=False)
        RX, RY, RZ, _ = _pt_dbl(RX, RY, RZ, want_t=False)
    eqx = _is_zero(_fmul(X, RZ) - _fmul(RX, Z))
    eqy = _is_zero(_fmul(Y, RZ) - _fmul(RY, Z))
    ok = ok_a & ok_r & eqx & eqy
    out_ref[...] = ok.astype(I32)


# --- host/XLA wrapper -------------------------------------------------------


def _digits65(limbs: jnp.ndarray) -> jnp.ndarray:
    """[B, 20] scalar limbs -> [65, B] 4-bit digits, most significant
    window FIRST (index 0 = window 64)."""
    outs = []
    for j in range(N_WIN):
        lo = 4 * j
        li, off = lo // BITS, lo % BITS
        d = limbs[..., li] >> off
        if off > BITS - 4 and li + 1 < NLIMBS:
            d = d | (limbs[..., li + 1] << (BITS - off))
        outs.append(d & 15)
    return jnp.stack(outs[::-1], axis=0)


def _digits52_signed(limbs: jnp.ndarray) -> jnp.ndarray:
    """[B, 20] scalar limbs -> [52, B] SIGNED 5-bit digits in [-16, 15],
    most significant window first.  Standard carry recoding of the
    unsigned base-32 digits: digits >= 16 borrow 32 and carry 1 into
    the next window.  Safe for ANY 32-byte value (S is attacker bytes,
    screened by the canonicity check only afterwards): the top window
    covers bits 255..259, of which only bit 255 can be set for a
    < 2^256 input, so raw[51] <= 1 and the incoming carry makes
    t <= 2 < 16 — the final carry is always absorbed."""
    raw = []
    for j in range(N_WIN5):
        lo = 5 * j
        li, off = lo // BITS, lo % BITS
        d = limbs[..., li] >> off
        if off > BITS - 5 and li + 1 < NLIMBS:
            d = d | (limbs[..., li + 1] << (BITS - off))
        raw.append(d & 31)
    carry = jnp.zeros_like(raw[0])
    outs = []
    for j in range(N_WIN5):              # lsb-first carry walk
        t = raw[j] + carry
        ge = t >= 16
        outs.append(jnp.where(ge, t - 32, t))
        carry = ge.astype(t.dtype)
    return jnp.stack(outs[::-1], axis=0)


def _ysign(b32: jnp.ndarray):
    """[B, 32] byte values -> (y limbs [B,20], sign [B])."""
    from agnes_tpu.crypto import field_jax as F
    b = b32.astype(I32)
    sign = b[..., 31] >> 7
    b = b.at[..., 31].set(b[..., 31] & 0x7F)
    return F.bytes32_to_limbs(b), sign


def _tile_limbs(a: jnp.ndarray, b_pad: int) -> jnp.ndarray:
    """[B, n] -> [n, b_pad//128, 128] (zero-padded)."""
    B, n = a.shape
    a = jnp.pad(a, ((0, b_pad - B), (0, 0)))
    return jnp.moveaxis(a, -1, 0).reshape(n, b_pad // 128, 128)


def _tile_flat(a: jnp.ndarray, b_pad: int) -> jnp.ndarray:
    B = a.shape[0]
    return jnp.pad(a, ((0, b_pad - B),)).reshape(b_pad // 128, 128)


def verify_batch_pallas(pub: jnp.ndarray, sig: jnp.ndarray,
                        msg_blocks: jnp.ndarray,
                        interpret: bool = False,
                        window: int = 4) -> jnp.ndarray:
    """Drop-in for ed25519_jax.verify_batch on TPU: pub [B,32] bytes,
    sig [B,64] bytes, msg_blocks [B,n,32] uint32 -> [B] bool.

    `window=4`: 65 unsigned 4-bit windows (the r3 kernel).  `window=5`:
    52 signed 5-bit windows — 20% fewer table adds for the same 260
    doublings (the r3-queued optimization; pick by measured rate on
    hardware, scripts/profile_verify.py).

    Always runs jitted (the ~100k-op kernel graph is unusable under
    eager dispatch).  The persistent compile cache is disabled
    framework-wide (utils/compile_cache.py post-mortem), so each
    process pays one compile per (shape, window, interpret) combo —
    reuse one batch shape per process."""
    if window not in (4, 5):
        raise ValueError(f"window must be 4 or 5: {window}")
    if interpret:
        # NEVER persist the interpret-mode executable: XLA's cache
        # writer segfaults intermittently serializing these ~100k-op
        # graphs (r4: reproduced across stack limits, single-threaded
        # codegen, and fresh cache dirs — put_executable_and_time every
        # time; see utils/compile_cache.py for the related, genuinely
        # fixed failure modes).  Interpret mode is tests-only; paying
        # the recompile beats a nondeterministic CI segfault.
        # jax LATCHES the enabled decision in module globals
        # (compilation_cache.is_cache_used "once per task"), so the
        # config flip only takes effect across a reset_cache().
        from jax._src import compilation_cache as _cc

        prev = jax.config.jax_enable_compilation_cache
        jax.config.update("jax_enable_compilation_cache", False)
        _cc.reset_cache()
        try:
            return _verify_jit(pub, sig, msg_blocks, True, window)
        finally:
            jax.config.update("jax_enable_compilation_cache", prev)
            _cc.reset_cache()
    return _verify_jit(pub, sig, msg_blocks, interpret, window)


@functools.partial(jax.jit, static_argnums=(3, 4))
def _verify_jit(pub, sig, msg_blocks, interpret: bool, window: int = 4):
    from agnes_tpu.crypto import scalar_jax as S
    from agnes_tpu.crypto import sha512_jax as sha

    B = pub.shape[0]
    if B == 0:
        return jnp.zeros((0,), bool)
    b_pad = -(-B // TILE) * TILE
    signed5 = window == 5
    n_win = N_WIN5 if signed5 else N_WIN

    k = S.barrett_reduce(S.digest_to_limbs(sha.sha512_blocks(msg_blocks)))
    s_limbs = S.scalar_from_bytes32(sig[..., 32:])
    ok_s = S.is_canonical(s_limbs)
    ya, sa = _ysign(pub)
    yr, sr = _ysign(sig[..., :32])

    digits = _digits52_signed if signed5 else _digits65
    sdig = digits(s_limbs)               # [n_win, B]
    kdig = digits(k)

    args = (
        _tile_limbs(ya, b_pad), _tile_flat(sa, b_pad),
        _tile_limbs(yr, b_pad), _tile_flat(sr, b_pad),
        jnp.pad(sdig, ((0, 0), (0, b_pad - B))
                ).reshape(n_win, b_pad // 128, 128),
        jnp.pad(kdig, ((0, 0), (0, b_pad - B))
                ).reshape(n_win, b_pad // 128, 128),
    )

    grid = (b_pad // TILE,)
    lspec = pl.BlockSpec((NLIMBS, BH, 128), lambda g: (0, g, 0),
                         memory_space=pltpu.VMEM)
    dspec = pl.BlockSpec((n_win, BH, 128), lambda g: (0, g, 0),
                         memory_space=pltpu.VMEM)
    fspec = pl.BlockSpec((BH, 128), lambda g: (g, 0),
                         memory_space=pltpu.VMEM)
    ok = pl.pallas_call(
        functools.partial(_verify_kernel, signed5=signed5),
        grid=grid,
        in_specs=[lspec, fspec, lspec, fspec, dspec, dspec],
        out_specs=fspec,
        out_shape=jax.ShapeDtypeStruct((b_pad // 128, 128), jnp.int32),
        interpret=interpret,
    )(*args)

    ok = ok.reshape(b_pad)[:B] > 0
    return ok & ok_s


from agnes_tpu.device import registry as _registry  # noqa: E402

_registry.register(_registry.EntrySpec(
    name="pallas_verify", fn=_verify_jit, jit=_verify_jit,
    statics=("interpret", "window"), hot=False,
    pallas_backends=("tpu", "interpret")))
