"""BLS12-381 G1/G2 aggregation kernels — the aggregate lane's O(N).

The serve plane's BLS lane (serve/bls_lane.py) splits each vote
class's verification into the O(N) part — aggregate N signer pubkeys
(G1, stake-weighted MSM) and N signature shares (G2, the same
weighted-MSM machinery) — and the O(1) part, two pairings through the
`bls_ref` oracle.  THIS module is the O(N) part on device:

* point arithmetic with the Renes–Costello–Batina COMPLETE projective
  addition for a = 0 short-Weierstrass curves (eprint 2015/1060,
  algorithm 7): branch-free, identity-safe, doubling-safe — exactly
  what vectorized bucket accumulation needs (buckets hold identities
  and equal points constantly), over `bls_field_jax`'s 12-bit-limb
  Barrett field (G1) and its Fp2 extension (G2);
* one registered jit entry, `bls_aggregate`: weights -> window digits
  -> `msm_jax.msm_generic` (the generalized Pippenger: shared
  doubling chain, sequential-scan bucket sums — see
  `bucket_sums_seq`'s rationale) for BOTH groups in one dispatch.
  Padding lanes carry weight 0 and fall into the excluded 0 bucket,
  so one compiled shape per ladder rung serves every class size.

Outputs stay PROJECTIVE (X, Y, Z limb arrays): the host converts to
affine with two python modular inversions per class (bls_ref) before
the pairing — the device never needs an inversion, a comparison, or a
canonical representative (bls_field_jax module docstring).

Weights are voting powers, capped at W_BITS bits (the lane screens);
the aggregate check this feeds is
`bls_ref.aggregate_verify_weighted`."""

from __future__ import annotations

from typing import NamedTuple, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from agnes_tpu.crypto import bls_field_jax as BF
from agnes_tpu.crypto import bls_ref as ref
from agnes_tpu.crypto import msm_jax as M
from agnes_tpu.crypto.bls_field_jax import (
    FV,
    FV2,
    I32,
    NLIMBS,
    RED_BOUND,
    fv2_add,
    fv2_in,
    fv2_mul,
    fv2_mul_small,
    fv2_out,
    fv2_reduce,
    fv2_sub,
    fv_add,
    fv_in,
    fv_mul,
    fv_mul_small,
    fv_reduce,
    fv_sub,
)

#: stake-weight width: voting powers above this are screened by the
#: lane at registration (2^24 per validator is far above any realistic
#: consensus power table; the MSM cost scales with it)
W_BITS = 24
W_LIMBS = -(-W_BITS // BF.BITS)          # 2
WINDOW_C = 4
N_WINDOWS = -(-W_BITS // WINDOW_C)       # 6


class G1P(NamedTuple):
    """Projective G1 point; each field [..., NLIMBS] int32 limbs."""

    x: jnp.ndarray
    y: jnp.ndarray
    z: jnp.ndarray


class G2P(NamedTuple):
    """Projective G2 point; each field [..., 2, NLIMBS] int32 limbs."""

    x: jnp.ndarray
    y: jnp.ndarray
    z: jnp.ndarray


def _one_limbs(shape: Tuple[int, ...]) -> jnp.ndarray:
    return jnp.zeros(shape + (NLIMBS,), I32).at[..., 0].set(1)


def g1_identity(shape: Tuple[int, ...]) -> G1P:
    z = jnp.zeros(shape + (NLIMBS,), I32)
    return G1P(x=z, y=_one_limbs(shape), z=z)


def g2_identity(shape: Tuple[int, ...]) -> G2P:
    z = jnp.zeros(shape + (2, NLIMBS), I32)
    one = z.at[..., 0, 0].set(1)
    return G2P(x=z, y=one, z=z)


def _rcb_add(p, q, *, add, sub, mul, red, b3_mul):
    """Renes–Costello–Batina 2015/1060 algorithm 7 (complete addition,
    a = 0), generic over the field op set — instantiated for Fp (G1)
    and Fp2 (G2).  Inputs/outputs are coordinate triples bounded by
    RED_BOUND (the scan-carry fixed point); the interleaved `red`
    calls keep every product inside the Barrett precondition, which
    `bls_field_jax` asserts statically at trace time."""
    x1, y1, z1 = p
    x2, y2, z2 = q
    t0 = mul(x1, x2)
    t1 = mul(y1, y2)
    t2 = mul(z1, z2)
    t3 = red(sub(mul(add(x1, y1), add(x2, y2)), add(t0, t1)))
    t4 = red(sub(mul(add(y1, z1), add(y2, z2)), add(t1, t2)))
    t6 = red(sub(mul(add(x1, z1), add(x2, z2)), add(t0, t2)))
    s3 = red(add(add(t0, t0), t0))               # 3 * X1X2
    t2b = b3_mul(t2)
    z3 = add(t1, t2b)
    t1b = sub(t1, t2b)
    y3 = b3_mul(t6)
    z3r = red(z3)
    x_out = red(sub(mul(t3, t1b), mul(t4, y3)))
    y_out = red(add(mul(y3, s3), mul(t1b, z3r)))
    z_out = red(add(mul(z3r, t4), mul(s3, t3)))
    return x_out, y_out, z_out


def g1_add(p: G1P, q: G1P) -> G1P:
    """Complete G1 addition; coords are RED_BOUND-bounded limbs."""
    def wrap(pt):
        return tuple(fv_in(c, RED_BOUND) for c in pt)

    x, y, z = _rcb_add(
        wrap(p), wrap(q),
        add=fv_add, sub=fv_sub, mul=fv_mul, red=fv_reduce,
        b3_mul=lambda t: fv_mul_small(t, 3 * ref.B_G1))
    return G1P(x=x.a, y=y.a, z=z.a)


def _fv2_b3(t: FV2) -> FV2:
    """t * 3*b' for b' = 4(1 + u): 12t(1 + u) =
    12(c0 - c1) + 12(c0 + c1)u, each component Barrett-reduced."""
    return FV2(fv_mul_small(fv_sub(t.c0, t.c1), 12),
               fv_mul_small(fv_add(t.c0, t.c1), 12))


def g2_add(p: G2P, q: G2P) -> G2P:
    """Complete G2 addition over Fp2; coords RED_BOUND-bounded."""
    def wrap(pt):
        return tuple(fv2_in(c, RED_BOUND) for c in pt)

    x, y, z = _rcb_add(
        wrap(p), wrap(q),
        add=fv2_add, sub=fv2_sub, mul=fv2_mul, red=fv2_reduce,
        b3_mul=_fv2_b3)
    return G2P(x=fv2_out(x), y=fv2_out(y), z=fv2_out(z))


# --- the registered aggregation entry ---------------------------------------

def n_windows_for(w_bits: int) -> int:
    """Windows needed for stake weights of `w_bits` bits (clamped to
    the registration-screened W_BITS cap).  STATIC per deployment:
    the key registry fixes its weight width at construction, so a
    uniform-stake validator set (w_bits=1) pays ONE window's bucket
    scan instead of six — the dominant per-class runtime term."""
    return -(-max(1, min(int(w_bits), W_BITS)) // WINDOW_C)


def bls_aggregate(pk: jnp.ndarray, sig: jnp.ndarray,
                  w: jnp.ndarray,
                  n_windows: int = N_WINDOWS,
                  pallas_field=False) -> Tuple[G1P, G2P]:
    """One vote class's O(N) aggregation in one dispatch.

    pk  [N, 2, NLIMBS] int32 — signer pubkeys, affine G1 limb coords
    sig [N, 4, NLIMBS] int32 — signature shares, affine G2
                               (x0, x1, y0, y1) limb coords
    w   [N, W_LIMBS]   int32 — stake weights as 12-bit limbs; weight 0
                               marks a padding lane (dropped by the
                               0-bucket exclusion, no mask needed)

    `n_windows` is STATIC (part of the compile key): the number of
    4-bit weight windows the MSM walks, `n_windows_for(w_bits)` of
    the deployment's weight width — every weight must fit
    `n_windows * WINDOW_C` bits (the key registry enforces it).

    Returns (agg_pk, agg_sig) PROJECTIVE: agg_pk = Σ [wᵢ] pkᵢ over G1,
    agg_sig = Σ [wᵢ] sigᵢ over G2 — the two MSMs whose outputs feed
    `bls_ref.aggregate_verify_weighted`'s single pairing-product
    check.  Shapes (+ n_windows, + pallas_field) are the compile key:
    the lane pads every class onto a ladder rung, so the jit cache
    holds one executable per rung.

    `pallas_field` is the STATIC kernel-lane knob (ISSUE 18): False
    traces the rolled-JAX field bodies, True the fused Pallas kernels
    (TPU), "interpret" the Pallas interpreter (CPU differentials).
    The serve lane resolves it ONCE (BlsLane.uses_pallas_field) and
    carries it in the retrace statics, so warming one lane and
    dispatching the other fails loudly at the sentinel, never as a
    live mid-serve compile."""
    with BF.field_backend(pallas_field):
        g1pts = G1P(x=pk[:, 0], y=pk[:, 1],
                    z=_one_limbs((pk.shape[0],)))
        g2x = jnp.stack([sig[:, 0], sig[:, 1]], axis=-2)
        g2y = jnp.stack([sig[:, 2], sig[:, 3]], axis=-2)
        g2pts = G2P(x=g2x, y=g2y, z=g2_identity((sig.shape[0],)).y)
        agg_pk = M.msm_generic(
            g1pts, w, n_windows, point_add=g1_add,
            identity=g1_identity, window_c=WINDOW_C, bits=BF.BITS)
        agg_sig = M.msm_generic(
            g2pts, w, n_windows, point_add=g2_add,
            identity=g2_identity, window_c=WINDOW_C, bits=BF.BITS)
        return agg_pk, agg_sig


bls_aggregate_jit = jax.jit(bls_aggregate,
                            static_argnames=("n_windows",
                                             "pallas_field"))

from agnes_tpu.device import registry as _registry  # noqa: E402

_registry.register(_registry.EntrySpec(
    name="bls_aggregate", fn=bls_aggregate, jit=bls_aggregate_jit,
    statics=("n_windows", "pallas_field"), hot=True,
    pallas_backends=("tpu", "interpret")))

# the kernel-lane census alias: SAME jit, `pallas_field` pinned on by
# the audit plan (jaxpr_audit.ENTRY_STATICS) so the fused-kernel graph
# gets its own traced-op baseline row next to the rolled one — the op
# budget the kernel lane must beat, policed like any other entry
_registry.register(_registry.EntrySpec(
    name="bls_aggregate_pallas", fn=bls_aggregate,
    jit=bls_aggregate_jit,
    statics=("n_windows", "pallas_field"), hot=False,
    pallas_backends=("tpu", "interpret")))


# --- host-side packing / unpacking ------------------------------------------

def pack_g1_rows(points) -> np.ndarray:
    """bls_ref affine G1 points -> [n, 2, NLIMBS] int32 (host)."""
    n = len(points)
    out = np.zeros((n, 2, NLIMBS), np.int32)
    for i, pt in enumerate(points):
        assert pt is not None, "identity pubkey cannot be aggregated"
        out[i, 0] = BF.to_limbs(pt[0])
        out[i, 1] = BF.to_limbs(pt[1])
    return out


def pack_g2_rows(points) -> np.ndarray:
    """bls_ref affine G2 points -> [n, 4, NLIMBS] int32 (host)."""
    n = len(points)
    out = np.zeros((n, 4, NLIMBS), np.int32)
    for i, pt in enumerate(points):
        assert pt is not None, "identity share cannot be aggregated"
        x, y = pt
        out[i, 0] = BF.to_limbs(x.c[0])
        out[i, 1] = BF.to_limbs(x.c[1])
        out[i, 2] = BF.to_limbs(y.c[0])
        out[i, 3] = BF.to_limbs(y.c[1])
    return out


def pack_weights(weights) -> np.ndarray:
    """Voting powers -> [n, W_LIMBS] int32 12-bit limbs.  Powers must
    fit W_BITS (the lane screens at registration)."""
    w = np.asarray(weights, np.int64)
    assert (w >= 0).all() and (w < (1 << W_BITS)).all(), \
        f"weights must fit {W_BITS} bits"
    out = np.zeros(w.shape + (W_LIMBS,), np.int32)
    for i in range(W_LIMBS):
        out[..., i] = (w >> (BF.BITS * i)) & BF.LMASK
    return out


def g1_from_device(p: G1P):
    """Projective limb output -> bls_ref affine G1 point (host: two
    python int mods + one inversion; None for the identity)."""
    z = BF.from_limbs(np.asarray(p.z)) % ref.P
    if z == 0:
        return None
    zi = pow(z, ref.P - 2, ref.P)
    return (BF.from_limbs(np.asarray(p.x)) * zi % ref.P,
            BF.from_limbs(np.asarray(p.y)) * zi % ref.P)


def g2_from_device(p: G2P):
    """Projective Fp2 limb output -> bls_ref affine G2 point."""
    z = ref.fq2(BF.from_limbs(np.asarray(p.z[..., 0, :])) % ref.P,
                BF.from_limbs(np.asarray(p.z[..., 1, :])) % ref.P)
    if z.is_zero():
        return None
    zi = z.inv()
    x = ref.fq2(BF.from_limbs(np.asarray(p.x[..., 0, :])) % ref.P,
                BF.from_limbs(np.asarray(p.x[..., 1, :])) % ref.P)
    y = ref.fq2(BF.from_limbs(np.asarray(p.y[..., 0, :])) % ref.P,
                BF.from_limbs(np.asarray(p.y[..., 1, :])) % ref.P)
    return (x * zi, y * zi)
