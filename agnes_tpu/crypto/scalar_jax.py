"""Scalars mod the ed25519 group order L, in 13-bit limbs (JAX).

The verification challenge k = SHA-512(R || A || M) is a 512-bit
integer that must be taken mod L = 2^252 + 27742...493 before the
double-scalar multiplication.  Classic Barrett reduction, limb-aligned:
with mu = floor(2^520 / L) (520 = 40 limbs exactly),

    q = floor(k * mu / 2^520)  in [floor(k/L) - 2, floor(k/L)]
    r = k - q*L                in [0, 3L)  ->  two conditional - L.

All products are (variable x compile-time-constant) convolutions done
as shifted multiply-adds over the constant's limbs; column sums stay
under 21 * 2^26 < 2^31, so everything is int32 like the field layer.

The reference has no scalar arithmetic (no crypto at all, SURVEY.md
§2.1); oracle for tests is plain Python `% L`.
"""

from __future__ import annotations

import jax.numpy as jnp

from agnes_tpu.crypto.field_jax import (
    BITS,
    I32,
    LMASK,
    _carry_chain,
    _geq,
    _raw_sub,
    bytes_to_limbs,
)

L = 2**252 + 27742317777372353535851937790883648493
N_HASH = 40                      # limbs for a 512-bit hash (520 bits)
N_SCALAR = 20                    # limbs for reduced scalars (260 bits)
MU = (1 << 520) // L             # 268 bits -> 21 limbs


def _const_limbs(x: int) -> list:
    out = []
    while x:
        out.append(x & LMASK)
        x >>= BITS
    return out or [0]


MU_LIMBS = _const_limbs(MU)
L_LIMBS_LIST = _const_limbs(L)
L_LIMBS = jnp.asarray(L_LIMBS_LIST + [0] * (N_SCALAR - len(L_LIMBS_LIST)),
                      I32)


def _mul_const(a: jnp.ndarray, const: list) -> jnp.ndarray:
    """[..., n] limbs times a constant (as limb list) -> [..., n+m-1]
    raw columns (unnormalized, < 2^31)."""
    n, m = a.shape[-1], len(const)
    acc = jnp.zeros(a.shape[:-1] + (n + m - 1,), I32)
    for j, cj in enumerate(const):
        if cj:
            acc = acc.at[..., j:j + n].add(a * jnp.asarray(cj, I32))
    return acc


def _chain(r: jnp.ndarray) -> jnp.ndarray:
    """Normalize non-negative raw columns; the final carry is appended
    as an extra limb (caller knows the true width)."""
    limbs, c = _carry_chain(r)
    return jnp.concatenate([limbs, c[..., None]], axis=-1)


def barrett_reduce(k: jnp.ndarray) -> jnp.ndarray:
    """[..., N_HASH] normalized limbs (value < 2^520) -> [..., N_SCALAR]
    limbs of k mod L (canonical, < L)."""
    t = _chain(_mul_const(k, MU_LIMBS))          # k*mu, limbs
    q = t[..., N_HASH:]                           # >> 520
    ql = _chain(_mul_const(q, L_LIMBS_LIST))[..., :N_HASH]
    r = _chain(k - ql)[..., :N_SCALAR]            # in [0, 3L), signed-safe
    for _ in range(2):
        ge = _geq(r, L_LIMBS)
        r = jnp.where(ge[..., None], _raw_sub(r, L_LIMBS), r)
    return r


def digest_to_limbs(digest: jnp.ndarray) -> jnp.ndarray:
    """sha512_jax digest ([..., 16] uint32, (hi, lo) big-endian word
    pairs) -> [..., N_HASH] limbs of the RFC 8032 little-endian int."""
    d = digest.astype(I32)
    bytes_le = []
    for j in range(64):
        t, b = j // 8, j % 8
        half = d[..., 2 * t] if b < 4 else d[..., 2 * t + 1]
        shift = 24 - 8 * (b % 4)
        bytes_le.append((half >> shift) & 0xFF)
    return bytes_to_limbs(jnp.stack(bytes_le, axis=-1), N_HASH)


def scalar_from_bytes32(b: jnp.ndarray) -> jnp.ndarray:
    """[..., 32] little-endian bytes -> [..., N_SCALAR] limbs (< 2^256,
    NOT reduced — use `is_canonical` for the S < L check)."""
    return bytes_to_limbs(b, N_SCALAR)


def is_canonical(s: jnp.ndarray) -> jnp.ndarray:
    """s < L (the RFC 8032 §5.1.7 malleability check)."""
    return ~_geq(s, L_LIMBS)


def bits_msb_first(s: jnp.ndarray, n_bits: int = 260) -> jnp.ndarray:
    """[..., n_limbs] limbs -> [n_bits, ...] bool, most significant bit
    first — the scan input for double-scalar multiplication."""
    idx = jnp.arange(n_bits - 1, -1, -1)
    limb, off = idx // BITS, idx % BITS
    bits = (s[..., limb] >> off) & 1
    return jnp.moveaxis(bits.astype(bool), -1, 0)
