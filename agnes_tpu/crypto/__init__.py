"""Ed25519 for the vote path.

The reference stubs all signing ("sign the vote", consensus_executor.rs:
35-41) and carries no signatures on `Vote` at all (lib.rs:23-27 — SURVEY
§2.1 "notably absent").  This package supplies the full signature
surface the build adds:

  ed25519_ref   pure-Python RFC 8032 implementation — the oracle every
                other implementation (C++ host, JAX batched) is
                differential-tested against.
  ed25519_jax   batched verification in JAX: packed-limb field
                arithmetic, vmapped double-scalar multiplication.
  sha512_jax    SHA-512 on device (uint32-pair word arithmetic) for the
                H(R || A || M) challenge hash.
  pallas_verify fused per-lane verification kernel (windowed Straus) —
                the canonical, deterministic verifier.
  msm_jax       MSM batch verification (random linear combination +
                segmented-scan Pippenger) — the honest-stream fast
                path; bisects to the per-lane verifier on failure.
"""

from agnes_tpu.crypto.ed25519_ref import (  # noqa: F401
    keypair,
    sign,
    verify,
)
from agnes_tpu.crypto.encoding import (  # noqa: F401
    VOTE_MSG_LEN,
    proposal_signing_bytes,
    vote_signing_bytes,
)


def host_sign(seed: bytes, msg: bytes) -> bytes:
    """Sign on the host: the C++ signer when the native build is
    available, the Python oracle otherwise.  The single fallback policy
    for every host-side consumer (executor, simulator, fixtures)."""
    try:
        from agnes_tpu.core import native
        return native.sign(seed, msg)
    except Exception:
        return sign(seed, msg)


def host_verify(pk: bytes, msg: bytes, sig: bytes) -> bool:
    """Verify on the host (C++ when available; see host_sign)."""
    try:
        from agnes_tpu.core import native
        return native.verify(pk, msg, sig)
    except Exception:
        return verify(pk, msg, sig)
