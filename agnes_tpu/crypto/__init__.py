"""Ed25519 for the vote path.

The reference stubs all signing ("sign the vote", consensus_executor.rs:
35-41) and carries no signatures on `Vote` at all (lib.rs:23-27 — SURVEY
§2.1 "notably absent").  This package supplies the full signature
surface the build adds:

  ed25519_ref   pure-Python RFC 8032 implementation — the oracle every
                other implementation (C++ host, JAX batched) is
                differential-tested against.
  ed25519_jax   batched verification in JAX: packed-limb field
                arithmetic, vmapped double-scalar multiplication.
  sha512_jax    SHA-512 on device (uint32-pair word arithmetic) for the
                H(R || A || M) challenge hash.
"""

from agnes_tpu.crypto.ed25519_ref import (  # noqa: F401
    keypair,
    sign,
    verify,
)
from agnes_tpu.crypto.encoding import (  # noqa: F401
    VOTE_MSG_LEN,
    proposal_signing_bytes,
    vote_signing_bytes,
)
