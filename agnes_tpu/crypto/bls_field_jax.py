"""GF(p) limb arithmetic for BLS12-381 in JAX — int32, 12-bit limbs.

The `field_jax.py` pattern pushed to 381 bits.  Two things change
against the Ed25519 field and both shape the design:

* **p is not pseudo-Mersenne**, so the cheap `2^260 === 608` fold is
  unavailable: products reduce by **Barrett** against
  ``mu = floor(2^768 / p)``.  Both constant multiplications inside the
  reduction (by mu and by p) are contractions against small constant
  banded matrices — one matmul each, the COLSUM idiom — never a
  per-limb update loop, and the quotient is taken on *loosely*
  normalized limbs (vectorized carry passes only).  The loose quotient
  under-shoots the true one by <= 2, so results land in [0, 4p) and
  STAY there: elements are "4p-reduced", never canonical.
  Canonicalization happens on the HOST (`from_limbs` + `% p` over
  python ints) — the device kernels (bls_jax) never need an inversion,
  a comparison, or a canonical representative.
* **32 limbs of 13 bits would overflow int32 column sums** (32 * 8800^2
  > 2^31), so the radix drops to 2^12: 33 limbs cover 396 bits, and
  column sums stay <= 33 * 4100 * 4095 < 2^31 for every product here.

Limbs are kept NON-NEGATIVE throughout (unlike field_jax's signed
limbs): the Barrett quotient is only one-sided-exact when the limbs
dropped by its shift are non-negative, so subtraction adds a
per-limb-dominating multiple of p first — field_jax's 64p SUB_K
spread, generalized to arbitrary static bounds (`_sub_spread`).  The
ONE sequential carry chain lives at the tail of `reduce_cols` (strict
output limbs are what keep every later bound small), and it runs over
24-bit limb PAIRS to halve its length.

Every value carries a STATIC python-int bound (`FV`): additions add
bounds, subtraction picks its spread from the subtrahend's bound, and
`fv_mul` auto-reduces operands until the product fits the Barrett
precondition (x < 2^768) — all decided at trace time, so a formula
change that would overflow fails the *trace* (and the jaxpr-audit
gate), not a hardware run, and the common case costs zero extra ops.

Oracle: `bls_ref` (plain python ints); see tests/test_bls.py.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Dict, List, Tuple

import numpy as np

import jax.numpy as jnp

from agnes_tpu.crypto.bls_ref import P

I32 = jnp.int32

BITS = 12
RADIX = 1 << BITS            # 4096
LMASK = RADIX - 1
NLIMBS = 33                  # 396 bits of headroom (4p < 2^384)
MU_SHIFT_LIMBS = 64          # Barrett shift: 2^768, limb-aligned
MU = (1 << (BITS * MU_SHIFT_LIMBS)) // P

#: loose-limb bound after a vectorized carry pass (strict is 4095)
LOOSE = RADIX + 8
#: Barrett precondition (slack for the loose-quotient error)
REDUCE_CAP = (1 << (BITS * MU_SHIFT_LIMBS)) - (1 << 762)
#: every reduce output obeys this value bound
RED_BOUND = 4 * P


def _const_limbs(x: int) -> List[int]:
    out = []
    while x:
        out.append(x & LMASK)
        x >>= BITS
    return out or [0]


# --- host <-> limb conversion ----------------------------------------------

def to_limbs(x: int) -> np.ndarray:
    """Python int in [0, 2^396) -> [NLIMBS] int32 (host helper)."""
    return np.asarray([(x >> (BITS * i)) & LMASK
                       for i in range(NLIMBS)], np.int32)


def ints_to_limbs(xs) -> np.ndarray:
    """Iterable of ints -> [len, NLIMBS] int32 (host helper)."""
    return np.stack([to_limbs(int(x)) for x in xs]) if len(xs) \
        else np.zeros((0, NLIMBS), np.int32)


def from_limbs(a) -> int:
    """Limb array (loose limbs welcome) -> python int; the caller
    takes `% P` — host-side canonicalization is one line of python."""
    arr = np.asarray(a)
    return sum(int(arr[..., i]) << (BITS * i)
               for i in range(arr.shape[-1]))


# --- vectorized carry passes ------------------------------------------------

def _vpass(r: jnp.ndarray) -> jnp.ndarray:
    """One exact vectorized carry pass over the whole limb axis
    (field_jax._vpass, fold=None): value preserved exactly, the top
    limb keeps its full value, signed carries borrow via the
    arithmetic shift.  Per-limb bound M maps to 4095 + M/2^12 + 1;
    non-negative input limbs stay non-negative."""
    lo = r & LMASK
    hi = r >> BITS
    shift_in = jnp.concatenate(
        [jnp.zeros_like(hi[..., :1]), hi[..., :-1]], axis=-1)
    lo = jnp.concatenate([lo[..., :-1], r[..., -1:]], axis=-1)
    return lo + shift_in


def _passes_needed(col_bound: int) -> int:
    n, m = 0, int(col_bound)
    while m > LOOSE:
        m = RADIX + m // RADIX + 1
        n += 1
    return max(n, 1)


def loosen(r: jnp.ndarray, col_bound: int) -> jnp.ndarray:
    """Columns (|col| <= col_bound) -> loose limbs (interior bound
    LOOSE), value preserved exactly."""
    for _ in range(_passes_needed(col_bound)):
        r = _vpass(r)
    return r


def _chain_strict(r: jnp.ndarray) -> jnp.ndarray:
    """Sequential signed carry chain -> strict limbs in [0, 2^12).
    Runs over 24-bit limb PAIRS (half the sequential steps); the
    caller guarantees the value is non-negative and fits, so the final
    carry is zero."""
    n = r.shape[-1]
    if n % 2:
        r = jnp.pad(r, [(0, 0)] * (r.ndim - 1) + [(0, 1)])
        n += 1
    s = r[..., 0::2] + (r[..., 1::2] << BITS)     # 24-bit superlimbs
    c = jnp.zeros_like(s[..., 0])
    outs = []
    mask24 = (1 << (2 * BITS)) - 1
    for k in range(n // 2):
        t = s[..., k] + c
        outs.append(t & mask24)
        c = t >> (2 * BITS)
    sup = jnp.stack(outs, axis=-1)
    lo = sup & LMASK
    hi = sup >> BITS
    return jnp.stack([lo, hi], axis=-1).reshape(r.shape[:-1] + (n,))


def _banded(const: List[int], n_in: int, n_out: int) -> jnp.ndarray:
    """[n_in, n_out] banded constant-multiplication matrix:
    (a @ M)[k] = sum_i a_i * const[k - i] — limb convolution by a
    fixed constant as ONE contraction.  Per-column terms <=
    len(const), so sums stay int32-safe for loose inputs."""
    m = np.zeros((n_in, n_out), np.int32)
    for i in range(n_in):
        for j, cj in enumerate(const):
            if cj and i + j < n_out:
                m[i, i + j] = cj
    return jnp.asarray(m)


_N65 = 2 * NLIMBS - 1
_MU_MAT = _banded(_const_limbs(MU), _N65, _N65 + len(_const_limbs(MU)))
_P_MAT = _banded(_const_limbs(P), NLIMBS, _N65)
# (the old flat-outer-product @ COLSUM contraction for variable x
# variable products is replaced by `_mul_cols`' shifted multiply-adds
# — same columns, ~65x less CPU arithmetic; the constant mu/p
# multiplies above stay banded matmuls, their bands are dense)


# --- backend selection (ISSUE 18) -------------------------------------------
#
# The Pallas kernel lane (crypto/pallas_field.py) is a TRACE-TIME
# swap: inside a `field_backend(...)` scope the two heavy bodies —
# the fused multiply+reduce of `fv_mul`/`fv_mul_pairs` and the
# `reduce_cols` carry chain — route to one `pallas_call` each instead
# of the rolled op soup.  The flag is a python global read while
# TRACING, so the choice bakes into the jitted graph: the registered
# BLS entries expose it as the `pallas_field=` static and the serve
# lane carries it in the retrace statics tuple (a warm/dispatch lane
# mismatch trips the armed sentinel, never a live mid-serve compile).
# Values: False = rolled JAX (the default, and the only lane off-TPU
# in production), True = compiled Pallas (TPU), "interpret" = the
# Pallas interpreter (CPU differentials and smoke benches).

_BACKEND = False


def current_backend():
    """The active field backend (False | True | "interpret")."""
    return _BACKEND


@contextlib.contextmanager
def field_backend(mode):
    """Scope the field-body backend for everything traced inside."""
    assert mode in (False, True, "interpret"), mode
    global _BACKEND
    prev = _BACKEND
    _BACKEND = mode
    try:
        yield
    finally:
        _BACKEND = prev


# --- Barrett reduction ------------------------------------------------------

def reduce_cols(cols: jnp.ndarray, col_bound: int) -> jnp.ndarray:
    """Raw NON-NEGATIVE columns (value < REDUCE_CAP) -> [NLIMBS]
    STRICT limbs of a representative < 4p of the same residue class.

    q = value(t[64:]) of the loosened t = x*mu drops only
    non-negative low limbs, so it under-shoots floor(x*mu / 2^768) by
    at most 2 and never overshoots — r = x - q*p stays in [0, 4p).
    The one sequential chain at the tail makes the output limbs
    strict, which is what keeps every downstream bound (and the
    subtraction spreads) small."""
    if _BACKEND is not False and cols.shape[-1] == NLIMBS:
        # the kernel lane fuses the whole loosen -> quotient ->
        # subtract -> chain into one pallas_call; only the
        # element-width stacks route (the 65-wide product columns of
        # fv_mul/fv_mul_pairs go through their own fused kernel)
        from agnes_tpu.crypto import pallas_field as _PF

        return _PF.reduce_rows(cols, col_bound,
                               interpret=_BACKEND == "interpret")
    x = loosen(cols, col_bound)
    n = x.shape[-1]
    if n < _N65:
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, _N65 - n)])
    t = x @ _MU_MAT
    t = loosen(t, len(_const_limbs(MU)) * LOOSE * LMASK)
    q = t[..., MU_SHIFT_LIMBS:MU_SHIFT_LIMBS + NLIMBS]
    ql = q @ _P_MAT
    r = x - loosen(ql, len(_const_limbs(P)) * LOOSE * LMASK)
    r = loosen(r, 2 * LOOSE * LMASK)
    return _chain_strict(r)[..., :NLIMBS]


# --- statically-bounded field values ---------------------------------------

@dataclasses.dataclass(frozen=True)
class FV:
    """A field value during tracing: non-negative loose limbs + STATIC
    value bound (a plain python int, trace-time only — the bound
    bookkeeping costs zero runtime ops)."""

    a: jnp.ndarray          # [..., NLIMBS] limbs, interior <= ~2*LOOSE
    bound: int

    def __post_init__(self):
        assert self.a.shape[-1] == NLIMBS, self.a.shape


def fv_in(arr: jnp.ndarray, bound: int = P) -> FV:
    """Wrap a kernel input (canonical host-packed limbs by default)."""
    return FV(arr, bound)


def fv_add(x: FV, y: FV) -> FV:
    return FV(_vpass(x.a + y.a), x.bound + y.bound)


#: memoized subtraction spreads, keyed by the subtrahend's top-limb
#: bound (docstring of _sub_spread)
_SPREADS: Dict[int, Tuple[np.ndarray, int]] = {}

#: per-limb bound of any element's interior limbs (strict reduce
#: outputs, one vpass after add/sub)
_ELEM_LIMB = 2 * LOOSE


def _sub_spread(y_bound: int) -> Tuple[np.ndarray, int]:
    """(limb array, value) of a multiple of p that per-limb dominates
    any element with value < y_bound: limbs 0..31 >= _ELEM_LIMB, the
    top region >= y_bound >> 384 + 2 — so x - y + spread has
    non-negative limbs everywhere (the field_jax 64p SUB_K spread,
    generalized).  Memoized by the top bound."""
    ytop = (int(y_bound) >> (BITS * (NLIMBS - 1))) + 2
    hit = _SPREADS.get(ytop)
    if hit is not None:
        return hit
    base = sum(_ELEM_LIMB << (BITS * i) for i in range(NLIMBS - 1))
    k = -(-(base + (ytop + 1) * (1 << (BITS * (NLIMBS - 1)))) // P)
    v = k * P
    rest = v - base
    assert rest >> (BITS * (NLIMBS - 1)) >= ytop
    limbs = np.asarray(
        [_ELEM_LIMB + ((rest >> (BITS * i)) & LMASK)
         for i in range(NLIMBS - 1)]
        + [rest >> (BITS * (NLIMBS - 1))], np.int64)
    assert (limbs < (1 << 30)).all(), "spread top limb overflow"
    assert sum(int(limbs[i]) << (BITS * i)
               for i in range(NLIMBS)) == v
    # memoize as a NUMPY constant: a jnp array built inside a scan/jit
    # trace would be a tracer, and caching a tracer across traces is a
    # leak (jnp ops consume numpy operands as constants directly)
    out = (np.asarray(limbs, np.int32), v)
    _SPREADS[ytop] = out
    return out


def fv_sub(x: FV, y: FV) -> FV:
    """x - y + spread(y.bound): value-equivalent mod p, limbs stay
    non-negative (Barrett's one-sided-quotient requirement)."""
    spread, v = _sub_spread(y.bound)
    return FV(_vpass(x.a - y.a + spread), x.bound + v)


def _mul_cols(xa: jnp.ndarray, ya: jnp.ndarray) -> jnp.ndarray:
    """Limb-convolution columns of x*y ([..., NLIMBS] each ->
    [..., 2*NLIMBS-1]): NLIMBS statically-shifted multiply-adds
    instead of the flat-outer-product @ _COLSUM contraction.  Exactly
    the same integer columns; the dense [NLIMBS^2, 65] matmul carries
    a ~65x arithmetic overhead (one nonzero per row) that the MXU
    absorbs on TPU but a CPU pays in full — and the serve smokes ARE
    the CPU story.  The pairing's per-dispatch wall dropped ~3x with
    this form; a Pallas kernel (ROADMAP) is the proper TPU answer."""
    parts = []
    for i in range(NLIMBS):
        term = xa[..., i:i + 1] * ya
        parts.append(jnp.pad(
            term, [(0, 0)] * (term.ndim - 1) + [(i, NLIMBS - 1 - i)]))
    out = parts[0]
    for p in parts[1:]:
        out = out + p
    return out


def _outer_cols(x: FV, y: FV) -> jnp.ndarray:
    return _mul_cols(x.a, y.a)


def _mul_reduce(xa: jnp.ndarray, ya: jnp.ndarray) -> jnp.ndarray:
    """Product limbs -> strict < 4p limbs: the ONE multiply+reduce
    body both `fv_mul` and `fv_mul_pairs` instantiate — rolled by
    default, one fused `pallas_call` on the kernel lane.  Both lanes
    return identical limb values (the interpret differential's
    contract)."""
    if _BACKEND is not False:
        from agnes_tpu.crypto import pallas_field as _PF

        return _PF.mul_rows(xa, ya, interpret=_BACKEND == "interpret")
    return reduce_cols(_mul_cols(xa, ya),
                       NLIMBS * _ELEM_LIMB * _ELEM_LIMB)


def fv_reduce(x: FV) -> FV:
    """Re-reduce a grown value below 4p."""
    assert x.bound < REDUCE_CAP
    if x.bound <= RED_BOUND:
        return x
    return FV(reduce_cols(x.a, _ELEM_LIMB + LMASK), RED_BOUND)


def fv_mul(x: FV, y: FV) -> FV:
    # auto-reduce grown operands until the product fits the Barrett
    # precondition — static, so the common case pays nothing and no
    # formula can silently overflow
    while x.bound * y.bound >= REDUCE_CAP:
        if x.bound >= y.bound:
            assert x.bound > RED_BOUND, "un-reducible operand pair"
            x = fv_reduce(x)
        else:
            y = fv_reduce(y)
    return FV(_mul_reduce(x.a, y.a), RED_BOUND)


def fv_mul_small(x: FV, k: int) -> FV:
    assert 0 < k * _ELEM_LIMB < (1 << 31) \
        and x.bound * k < REDUCE_CAP
    return FV(reduce_cols(x.a * jnp.asarray(k, I32), k * _ELEM_LIMB),
              RED_BOUND)


def fv_reduce_stack(fvs: List[FV]) -> List[FV]:
    """Re-reduce a LIST of values below 4p with ONE stacked
    `reduce_cols` instantiation (the graph-diet companion of
    `fv_mul_pairs`: per-component `fv_reduce` calls were the
    dominant trace-size term of the tower's combine steps).  All
    inputs are reduced unconditionally — a caller batching mixed
    bounds trades a little runtime for one shared body."""
    for x in fvs:
        assert x.bound < REDUCE_CAP
    stacked = jnp.stack([x.a for x in fvs], axis=-2)
    out = reduce_cols(stacked, _ELEM_LIMB + LMASK)
    return [FV(out[..., k, :], RED_BOUND) for k in range(len(fvs))]


def fv_mul_pairs(pairs) -> List[FV]:
    """[(x, y), ...] -> [x*y, ...] with ONE stacked outer-product /
    colsum / Barrett-reduce instantiation for the whole list — the
    shared-subexpression limb kernel of the graph diet (ISSUE 13):
    a tower multiply that funnels its K field products through here
    costs a single `reduce_cols` body in the traced graph instead of
    K copies of it, and the eager path pays one batched matmul
    instead of K small ones.  Operands must share their leading batch
    shape.  Pairs over the Barrett precondition auto-reduce like
    `fv_mul` — but through ONE further stacked reduce over every
    grown operand (reducing both sides of a hot pair lands at
    4p * 4p = 16p^2, always inside the precondition)."""
    fixed = [list(p) for p in pairs]
    marks = []
    for i, (x, y) in enumerate(fixed):
        if x.bound * y.bound < REDUCE_CAP:
            continue
        hit = False
        for j in (0, 1):
            if fixed[i][j].bound > RED_BOUND:
                marks.append((i, j))
                hit = True
        assert hit, "un-reducible operand pair"
    if marks:
        red = fv_reduce_stack([fixed[i][j] for i, j in marks])
        for k, (i, j) in enumerate(marks):
            fixed[i][j] = red[k]
    for x, y in fixed:
        assert x.bound * y.bound < REDUCE_CAP
    xa = jnp.stack([x.a for x, _ in fixed], axis=-2)
    ya = jnp.stack([y.a for _, y in fixed], axis=-2)
    out = _mul_reduce(xa, ya)
    return [FV(out[..., k, :], RED_BOUND) for k in range(len(fixed))]


#: static bit table of p - 2, MSB first (the Fermat-inversion chain)
_INV_EXP_BITS = tuple((P - 2) >> i & 1
                      for i in range((P - 2).bit_length() - 1, -1, -1))


def fv_inv(x: FV) -> FV:
    """x^(p-2) — the modular inverse (maps 0 to 0), as a ROLLED
    square-and-multiply over the static bits of p - 2: the traced
    graph holds ONE squaring and ONE multiply body however long the
    exponent (the rolled-loop discipline the pairing's final
    exponentiation is built on).  The multiply runs every iteration
    against `select(bit, x, 1)` so the body stays branch-free."""
    x = fv_reduce(x)
    one = jnp.zeros_like(x.a).at[..., 0].set(1)
    bits = jnp.asarray(_INV_EXP_BITS[1:], jnp.bool_)   # MSB consumed
    xsel = x.a

    def body(i, acc):
        sq = fv_mul_pairs([(FV(acc, RED_BOUND), FV(acc, RED_BOUND))])[0]
        rhs = jnp.where(bits[i], xsel, one)
        return fv_mul_pairs([(sq, FV(rhs, RED_BOUND))])[0].a

    import jax

    acc = jax.lax.fori_loop(0, len(_INV_EXP_BITS) - 1, body, x.a)
    return FV(acc, RED_BOUND)


# --- canonical comparison (device verdicts) ---------------------------------
#
# Elements are 4p-reduced by design and the kernels never compare —
# EXCEPT the pairing verdict, which must decide `== 1 in Fp12` and
# `Z == 0` (identity inputs) on device.  A `reduce_cols` output is a
# STRICT-limb representative < 4p, so its residue class has exactly
# the four candidates value + {0,1,2,3}p, each with a unique strict
# limb pattern: equality against a constant is four vector compares.

def fv_strict(x: FV) -> jnp.ndarray:
    """Strict limbs of a < 4p representative of x's residue class."""
    assert x.bound < REDUCE_CAP
    return reduce_cols(x.a, _ELEM_LIMB + LMASK)


def strict_eq_mod_p(strict: jnp.ndarray, value: int) -> jnp.ndarray:
    """strict (< 4p, strict limbs) == value (mod p) -> [...] bool."""
    eq = None
    for k in range(4):
        c = to_limbs(value % P + k * P)
        e = jnp.all(strict == c, axis=-1)
        eq = e if eq is None else (eq | e)
    return eq


def fv_eq_mod_p(x: FV, value: int) -> jnp.ndarray:
    return strict_eq_mod_p(fv_strict(x), value)


# --- Fp2 (u^2 = -1), components as FV pairs ---------------------------------

@dataclasses.dataclass(frozen=True)
class FV2:
    c0: FV
    c1: FV


def fv2_in(arr: jnp.ndarray, bound: int = P) -> FV2:
    """[..., 2, NLIMBS] -> FV2."""
    return FV2(FV(arr[..., 0, :], bound), FV(arr[..., 1, :], bound))


def fv2_add(x: FV2, y: FV2) -> FV2:
    return FV2(fv_add(x.c0, y.c0), fv_add(x.c1, y.c1))


def fv2_sub(x: FV2, y: FV2) -> FV2:
    return FV2(fv_sub(x.c0, y.c0), fv_sub(x.c1, y.c1))


def fv2_mul_pairs_expand(x: FV2, y: FV2):
    """The three Karatsuba operand pairs of x*y over u^2 = -1 —
    v0 = a0b0, v1 = a1b1, v2 = (a0+a1)(b0+b1) — for a caller that
    collects several Fp2 products into ONE `fv_mul_pairs` call (the
    tower's graph diet); `fv2_mul_pairs_combine` folds the three
    products back into the Fp2 result."""
    return [(x.c0, y.c0), (x.c1, y.c1),
            (fv_add(x.c0, x.c1), fv_add(y.c0, y.c1))]


def fv2_mul_pairs_combine(v0: FV, v1: FV, v2: FV) -> FV2:
    """c0 = v0 - v1, c1 = v2 - v0 - v1 (Karatsuba recombination)."""
    return FV2(fv_sub(v0, v1), fv_sub(v2, fv_add(v0, v1)))


def fv2_mul(x: FV2, y: FV2) -> FV2:
    """Karatsuba over u^2 = -1, its three field products funneled
    through the ONE stacked Barrett body (`fv_mul_pairs`) — the
    graph-diet rewire (ISSUE 13): an Fp2 product costs a single
    reduce instantiation where it used to cost three (the dominant
    trace-size term of the G2 lane's point-add bodies)."""
    v0, v1, v2 = fv_mul_pairs(fv2_mul_pairs_expand(x, y))
    return fv2_mul_pairs_combine(v0, v1, v2)


def fv2_square(x: FV2) -> FV2:
    """(a0 + a1 u)^2 = (a0+a1)(a0-a1) + 2 a0a1 u — TWO stacked
    products (complex-squaring trick) instead of a mul's three."""
    p0, p1 = fv_mul_pairs([
        (fv_add(x.c0, x.c1), fv_sub(x.c0, x.c1)), (x.c0, x.c1)])
    return FV2(p0, fv_add(p1, p1))


def fv2_neg(x: FV2) -> FV2:
    z = FV(jnp.zeros_like(x.c0.a), 1)
    return FV2(fv_sub(z, x.c0), fv_sub(z, x.c1))


def fv2_conj(x: FV2) -> FV2:
    """a0 - a1 u: the p-power Frobenius on Fp2."""
    z = FV(jnp.zeros_like(x.c1.a), 1)
    return FV2(x.c0, fv_sub(z, x.c1))


def fv2_inv(x: FV2) -> FV2:
    """(a0 - a1 u) / (a0^2 + a1^2), the denominator inverted by the
    Fermat chain (`fv_inv`); maps 0 to 0 — the pairing's degenerate
    inputs collapse to a rejecting verdict, never a crash."""
    s0, s1 = fv_mul_pairs([(x.c0, x.c0), (x.c1, x.c1)])
    n = fv_inv(fv_add(s0, s1))
    z = FV(jnp.zeros_like(x.c1.a), 1)
    c0, c1 = fv_mul_pairs([(x.c0, n), (fv_sub(z, x.c1), n)])
    return FV2(c0, c1)


def fv2_mul_small(x: FV2, k: int) -> FV2:
    return FV2(fv_mul_small(x.c0, k), fv_mul_small(x.c1, k))


def fv2_reduce(x: FV2) -> FV2:
    return FV2(fv_reduce(x.c0), fv_reduce(x.c1))


def fv2_out(x: FV2) -> jnp.ndarray:
    """FV2 -> [..., 2, NLIMBS]."""
    return jnp.stack([x.c0.a, x.c1.a], axis=-2)
