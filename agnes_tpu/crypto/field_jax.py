"""GF(2^255 - 19) limb arithmetic in JAX, int32-only.

Representation chosen for the TPU's 32-bit vector unit: 20 little-endian
limbs of 13 bits (radix 2^13, 260 bits of headroom).  The bounds work
out so that *no intermediate ever leaves int32*:

  - schoolbook product terms: (2^13-1)^2 < 2^26
  - a product column sums at most 20 terms: < 20 * 2^26 < 2^31
  - the high product half is carry-normalized to 13-bit limbs *before*
    the mod-p fold, so the fold multiplier 608 = 19 * 2^5 (from
    2^260 = 2^5 * 2^255 = 32 * 2^255 === 32*19 mod p) stays < 2^23.

Elements are kept *partially reduced* — limbs < 2^13, value < 2^260,
possibly >= p — through all arithmetic; `freeze` produces the canonical
value only for compares/encodings.  Subtraction adds 64p (spread across
limbs so every limb of the constant is >= 6976) before the carry chain,
which keeps totals positive for any pair of partially-reduced inputs;
signed int32 carries (arithmetic shift) absorb the per-limb slack.

The batch axis is leading and everything is elementwise or a contraction
against small constant matrices, so `jit(vmap(...))` vectorizes cleanly;
the column sums of `mul` are a [.., 400] x [400, 39] constant matmul XLA
can put on the MXU.

Oracle: `ed25519_ref` (plain Python ints); see tests/test_field_jax.py.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

I32 = jnp.int32

BITS = 13
RADIX = 1 << BITS          # 8192
LMASK = RADIX - 1
NLIMBS = 20                # 260 bits
P = 2**255 - 19
FOLD = 608                 # 2^260 mod p = 32 * 19

# 64p = 2^261 - 1216, spread so every limb is a valid 13-bit-ish positive
# constant: limb0 = 8192-1216, limbs 1..18 = 8191, limb19 = 2^14 - 1.
_SUB_K = np.full(NLIMBS, LMASK, np.int32)
_SUB_K[0] = RADIX - 1216
_SUB_K[NLIMBS - 1] = (1 << 14) - 1
SUB_K = jnp.asarray(_SUB_K)
assert sum(int(_SUB_K[i]) << (BITS * i) for i in range(NLIMBS)) == 64 * P

# column-sum matrix: flat outer-product index (i*NLIMBS+j) -> column i+j
_COLS = 2 * NLIMBS - 1
_M = np.zeros((NLIMBS * NLIMBS, _COLS), np.int32)
for _i in range(NLIMBS):
    for _j in range(NLIMBS):
        _M[_i * NLIMBS + _j, _i + _j] = 1
COLSUM = jnp.asarray(_M)


# --- host <-> limb conversion ----------------------------------------------

def to_limbs(x: int) -> jnp.ndarray:
    """Python int -> [NLIMBS] int32 (host helper)."""
    return jnp.asarray([(x >> (BITS * i)) & LMASK for i in range(NLIMBS)],
                       I32)


def from_limbs(a) -> int:
    """[NLIMBS] limbs -> Python int (host helper; no mod-p)."""
    arr = np.asarray(a, np.int64)
    return sum(int(arr[..., i]) << (BITS * i) for i in range(NLIMBS))


def bytes_to_limbs(b: jnp.ndarray, n_limbs: int) -> jnp.ndarray:
    """[..., n_bytes] uint8/int32 little-endian bytes -> [..., n_limbs]
    13-bit limbs.  Pure bit-slicing, works under jit: limb i covers bits
    [13i, 13i+13), i.e. 2-3 consecutive bytes."""
    n_bytes = b.shape[-1]
    b = b.astype(I32)
    out = []
    for i in range(n_limbs):
        lo_bit = BITS * i
        byte0, off = lo_bit // 8, lo_bit % 8
        v = b[..., byte0] >> off
        got = 8 - off
        k = 1
        while got < BITS:
            if byte0 + k < n_bytes:
                v = v | (b[..., byte0 + k] << got)
            got += 8
            k += 1
        out.append(v & LMASK)
    return jnp.stack(out, axis=-1)


def bytes32_to_limbs(b: jnp.ndarray) -> jnp.ndarray:
    """[..., 32] little-endian bytes -> [..., NLIMBS] field limbs."""
    return bytes_to_limbs(b, NLIMBS)


def limbs_to_bytes32(a: jnp.ndarray) -> jnp.ndarray:
    """[..., NLIMBS] *frozen* limbs -> [..., 32] int32 little-endian
    bytes (values 0..255)."""
    out = []
    for byte in range(32):
        lo_bit = 8 * byte
        limb0, off = lo_bit // BITS, lo_bit % BITS
        v = a[..., limb0] >> off
        got = BITS - off
        if got < 8 and limb0 + 1 < NLIMBS:
            v = v | (a[..., limb0 + 1] << got)
        out.append(v & 0xFF)
    return jnp.stack(out, axis=-1)


# --- carry normalization ----------------------------------------------------

def _carry_chain(r: jnp.ndarray):
    """One signed sequential carry chain over the last axis: returns
    (limbs in [0, 2^13), carry out).  Arithmetic right-shift makes
    negative columns borrow correctly.  Shared by every normalizer here
    and by scalar_jax — fix bounds bugs in ONE place."""
    c = jnp.zeros_like(r[..., 0])
    outs = []
    for k in range(r.shape[-1]):
        t = r[..., k] + c
        outs.append(t & LMASK)
        c = t >> BITS
    return jnp.stack(outs, axis=-1), c


def carry(r: jnp.ndarray) -> jnp.ndarray:
    """Normalize [..., NLIMBS] int32 columns (|col| < 2^30, total value
    non-negative) to *weakly* normalized limbs in [0, 2^13 + 16),
    preserving the value mod p.

    One signed chain, a *608 wrap fold into limb 0, and a 3-step
    ripple.  This is the hot-path normalizer: weak limbs are safe for
    every field op (products (2^13+16)^2 * 20 terms still fit int32;
    `sub`'s 64p spread still dominates per-limb), and the boundaries
    that need strict limbs (compares, byte packing) go through
    `strict_carry`/`freeze`.  Bounds: the wrap carry c1 <= 2^19, so the
    fold adds < 2^28 to limb 0; rippling limbs 0..2 then leaves limbs
    1..3 within +16 of 2^13.  Callers must keep the total non-negative
    (`sub` adds 64p for exactly this reason)."""
    r, c = _carry_chain(r)
    r = r.at[..., 0].add(FOLD * c)
    for k in range(3):
        t = r[..., k]
        r = r.at[..., k].set(t & LMASK)
        r = r.at[..., k + 1].add(t >> BITS)
    return r


def strict_carry(r: jnp.ndarray) -> jnp.ndarray:
    """Full normalization to limbs in [0, 2^13): three (chain + wrap
    fold) passes.  Pass-1's wrap carry is <= 2^19; each chain masks
    limbs below 2^13 so passes 2-3 see wrap carries <= 1, and when the
    last chain still carries, the residual value is <= 607 so the final
    fold cannot push limb 0 back over 2^13."""
    for _ in range(3):
        r, c = _carry_chain(r)
        r = r.at[..., 0].add(FOLD * c)
    return r


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return carry(a + b)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return carry(a - b + SUB_K)


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Field multiply: outer product, column sums via the constant
    COLSUM contraction, high-half carry, *608 fold, carry."""
    prod = a[..., :, None] * b[..., None, :]           # [..., 20, 20] < 2^26
    flat = prod.reshape(prod.shape[:-2] + (NLIMBS * NLIMBS,))
    cols = flat @ COLSUM                               # [..., 39] < 2^31
    lo, hi = cols[..., :NLIMBS], cols[..., NLIMBS:]
    # normalize the high half to 13-bit limbs before scaling by 608
    c = jnp.zeros_like(hi[..., 0])
    hl = []
    for k in range(_COLS - NLIMBS):
        t = hi[..., k] + c
        hl.append(t & LMASK)
        c = t >> BITS
    hi_n = jnp.stack(hl + [c], axis=-1)                # [..., 20] < 2^13 (+c)
    return carry(lo + FOLD * hi_n)


def sqr(a: jnp.ndarray) -> jnp.ndarray:
    return mul(a, a)


def mul_small(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """Multiply by a small constant (k < 2^17)."""
    return carry(a * jnp.asarray(k, I32))


def one_like(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.zeros_like(a).at[..., 0].set(1)


def pow_p(a: jnp.ndarray, e: int) -> jnp.ndarray:
    """a^e by left-to-right square-and-multiply over the static exponent
    bits, as a `lax.scan` — a 255-squaring chain unrolled into the graph
    compiles in O(minutes) on XLA, so the loop must be rolled (one body
    compile, sequential execution; the batch axis keeps the VPU fed)."""
    bits = jnp.asarray([(e >> i) & 1 for i in
                        reversed(range(e.bit_length()))], bool)

    def body(r, bit):
        r = sqr(r)
        return jnp.where(bit, mul(r, a), r), None

    r, _ = jax.lax.scan(body, one_like(a), bits)
    return r


def inv(a: jnp.ndarray) -> jnp.ndarray:
    return pow_p(a, P - 2)


def freeze(a: jnp.ndarray) -> jnp.ndarray:
    """Canonical representative in [0, p) with strict limbs.  After
    strict normalization the value is < 2^260 < 33p, so branch-free
    conditional subtraction of 16p, 8p, 4p, 2p, p, p reduces it."""
    a = strict_carry(a)
    for m in (16, 8, 4, 2, 1, 1):
        mp = to_limbs(m * P)
        ge = _geq(a, mp)
        a = jnp.where(ge[..., None], _raw_sub(a, mp), a)
    return a


def _raw_sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a - b for a >= b, both limb-normalized: signed chain, no fold.
    Generic over the limb count (also used for mod-L scalars)."""
    return _carry_chain(a - b)[0]


def _geq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a >= b on normalized limbs (lexicographic from the top).
    Generic over the limb count."""
    gt = jnp.zeros(a.shape[:-1], bool)
    eq = jnp.ones(a.shape[:-1], bool)
    for k in reversed(range(a.shape[-1])):
        ak, bk = a[..., k], b[..., k]
        gt = gt | (eq & (ak > bk))
        eq = eq & (ak == bk)
    return gt | eq


def eq_mod_p(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a == b (mod p) for partially-reduced inputs."""
    fa, fb = freeze(a), freeze(b)
    return jnp.all(fa == fb, axis=-1)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(freeze(a) == 0, axis=-1)
