"""GF(2^255 - 19) limb arithmetic in JAX, int32-only, *signed* limbs.

Representation chosen for the TPU's 32-bit vector unit: 20 little-endian
limbs of 13 bits (radix 2^13, 260 bits of headroom), each limb a SIGNED
int32 kept in [-8800, 8800] ("weak" range).  Signed limbs buy two
things: subtraction is simply `carry(a - b)` — no 64p offset — and the
carry normalizer is a handful of *vectorized whole-limb-axis passes*
(mask, shift, roll, add) instead of a 20-step sequential chain.  That
matters because the double-scalar-mult scan body executes ~19 field
muls per bit, and on TPU the runtime is dominated by op dispatch, not
FLOPs: the sequential chains made verification ~25x slower.

Bounds (everything stays in int32):

  - schoolbook product terms: 8800^2 < 2^27; column sums of <= 20
    terms: 20 * 8800^2 < 1.55e9 < 2^31 (sign-magnitude, signed-safe)
  - one vectorized carry pass maps per-limb bound M to
    8191 + M/2^13 + 1, converging to ~8193 in 3-4 passes from 2^30;
    the top limb's wrap folds into limb 0 times 608 = 2^260 mod p
  - mul normalizes the 21-limb high half before scaling by 608; its
    top limb is bounded by value >> 260 <= 2^6, so the 2^260 === 608
    double-fold term 608*608*h20 also fits int32.

Values are partially reduced (|value| < 2^260.1, any residue class);
`freeze` adds 64p, exact-normalizes and canonicalizes to [0, p) for
compares/encodings only.  The batch axis is leading and everything is
elementwise or a contraction against small constant matrices, so
`jit(vmap(...))` vectorizes cleanly.

Oracle: `ed25519_ref` (plain Python ints); see tests/test_field_jax.py.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

I32 = jnp.int32

BITS = 13
RADIX = 1 << BITS          # 8192
LMASK = RADIX - 1
NLIMBS = 20                # 260 bits
P = 2**255 - 19
FOLD = 608                 # 2^260 mod p = 32 * 19

# 64p = 2^261 - 1216 spread over 20 limbs (limb19 oversized at 2^14-1):
# freeze adds it to make signed values positive before exact reduction.
_SUB_K = np.full(NLIMBS, LMASK, np.int32)
_SUB_K[0] = RADIX - 1216
_SUB_K[NLIMBS - 1] = (1 << 14) - 1
SUB_K = jnp.asarray(_SUB_K)
assert sum(int(_SUB_K[i]) << (BITS * i) for i in range(NLIMBS)) == 64 * P

# column-sum matrix: flat outer-product index (i*NLIMBS+j) -> column i+j
_COLS = 2 * NLIMBS - 1
_M = np.zeros((NLIMBS * NLIMBS, _COLS), np.int32)
for _i in range(NLIMBS):
    for _j in range(NLIMBS):
        _M[_i * NLIMBS + _j, _i + _j] = 1
COLSUM = jnp.asarray(_M)


# --- host <-> limb conversion ----------------------------------------------

def to_limbs(x: int) -> jnp.ndarray:
    """Python int -> [NLIMBS] int32 (host helper)."""
    return jnp.asarray([(x >> (BITS * i)) & LMASK for i in range(NLIMBS)],
                       I32)


def from_limbs(a) -> int:
    """[NLIMBS] limbs -> Python int (host helper; no mod-p)."""
    arr = np.asarray(a, np.int64)
    return sum(int(arr[..., i]) << (BITS * i) for i in range(NLIMBS))


def bytes_to_limbs(b: jnp.ndarray, n_limbs: int) -> jnp.ndarray:
    """[..., n_bytes] uint8/int32 little-endian bytes -> [..., n_limbs]
    13-bit limbs.  Pure bit-slicing, works under jit: limb i covers bits
    [13i, 13i+13), i.e. 2-3 consecutive bytes."""
    n_bytes = b.shape[-1]
    b = b.astype(I32)
    out = []
    for i in range(n_limbs):
        lo_bit = BITS * i
        byte0, off = lo_bit // 8, lo_bit % 8
        v = b[..., byte0] >> off
        got = 8 - off
        k = 1
        while got < BITS:
            if byte0 + k < n_bytes:
                v = v | (b[..., byte0 + k] << got)
            got += 8
            k += 1
        out.append(v & LMASK)
    return jnp.stack(out, axis=-1)


def bytes32_to_limbs(b: jnp.ndarray) -> jnp.ndarray:
    """[..., 32] little-endian bytes -> [..., NLIMBS] field limbs."""
    return bytes_to_limbs(b, NLIMBS)


def limbs_to_bytes32(a: jnp.ndarray) -> jnp.ndarray:
    """[..., NLIMBS] *frozen* limbs -> [..., 32] int32 little-endian
    bytes (values 0..255)."""
    out = []
    for byte in range(32):
        lo_bit = 8 * byte
        limb0, off = lo_bit // BITS, lo_bit % BITS
        v = a[..., limb0] >> off
        got = BITS - off
        if got < 8 and limb0 + 1 < NLIMBS:
            v = v | (a[..., limb0 + 1] << got)
        out.append(v & 0xFF)
    return jnp.stack(out, axis=-1)


# --- carry normalization ----------------------------------------------------

def _carry_chain(r: jnp.ndarray):
    """One signed sequential carry chain over the last axis: returns
    (limbs in [0, 2^13), carry out).  Arithmetic right-shift makes
    negative columns borrow correctly.  Shared by every normalizer here
    and by scalar_jax — fix bounds bugs in ONE place."""
    c = jnp.zeros_like(r[..., 0])
    outs = []
    for k in range(r.shape[-1]):
        t = r[..., k] + c
        outs.append(t & LMASK)
        c = t >> BITS
    return jnp.stack(outs, axis=-1), c


def _vpass(r: jnp.ndarray, fold: int | None = FOLD) -> jnp.ndarray:
    """One vectorized carry pass over the whole limb axis: ~5 ops, no
    sequential chain.  value(out) == value(in) exactly (fold=None — the
    top limb is left intact so nothing is shifted off the end) or mod p
    (fold wraps the top limb's carry into limb 0 as carry * fold).

    Works for signed limbs: `& LMASK` keeps the two's-complement low
    bits and the arithmetic `>> BITS` carries the signed remainder, so
    lo + (hi << 13) reconstructs the input limb exactly.  With per-limb
    bound M in, the non-top out bound is 8191 + M/2^13 + 1 — a few
    passes converge to ~8.2k regardless of M."""
    lo = r & LMASK
    hi = r >> BITS                 # arithmetic shift: signed carries
    shift_in = jnp.concatenate(
        [jnp.zeros_like(hi[..., :1]), hi[..., :-1]], axis=-1)
    if fold is None:
        # exact mode: the top limb keeps its full value (not masked,
        # nothing shifted off the end), still receives the carry below
        lo = jnp.concatenate([lo[..., :-1], r[..., -1:]], axis=-1)
        return lo + shift_in
    return lo + shift_in.at[..., 0].add(hi[..., -1] * fold)


def carry(r: jnp.ndarray, passes: int = 4) -> jnp.ndarray:
    """Normalize [..., NLIMBS] signed int32 columns (|col| < 2^31 / 20)
    to weak limbs (|limb| <= 8208), preserving the value mod p.

    Vectorized passes only — the hot-path normalizer inside the
    double-scalar-mult scan.  4 passes handle |col| up to ~2^30 (mul
    output, including the fold's 608 * 2^17 landing on limb 0); callers
    with small inputs (add/sub: |col| < 2^15) may pass `passes=2`.
    Limbs may end negative (bounded ~-1300 via the final pass's fold on
    limb 0, tiny elsewhere); all consumers are bound-safe under
    |limb| <= 8800; exact non-negative limbs come from
    `strict_carry`/`freeze` at the boundaries."""
    for _ in range(passes):
        r = _vpass(r)
    return r


def strict_carry(r: jnp.ndarray) -> jnp.ndarray:
    """Exact normalization to limbs in [0, 2^13).  Vectorized passes
    first (cheap convergence to ~[-2, 8193]), then one sequential
    signed chain with wrap fold; the chain's outputs are masked
    non-negative and its final wrap is <= 1 with a tiny limb 0, so one
    fold cannot overflow.  Caller must guarantee the total VALUE is
    non-negative (freeze adds 64p first for exactly that)."""
    for _ in range(3):
        r = _vpass(r)
    r, c = _carry_chain(r)
    r = r.at[..., 0].add(FOLD * c)
    r, c2 = _carry_chain(r)       # clears any ripple from the fold
    return r.at[..., 0].add(FOLD * c2)


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return carry(a + b, passes=2)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Signed limbs make subtraction offset-free (no 64p constant)."""
    return carry(a - b, passes=2)


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Field multiply: outer product, column sums via the constant
    COLSUM contraction, vectorized high-half normalize, *608 fold,
    vectorized carry."""
    prod = a[..., :, None] * b[..., None, :]        # [..., 20, 20] < 2^27
    flat = prod.reshape(prod.shape[:-2] + (NLIMBS * NLIMBS,))
    cols = flat @ COLSUM                            # [..., 39] |.| < 2^31
    lo, hi = cols[..., :NLIMBS], cols[..., NLIMBS:]
    # high half as its own 21-limb number (|value| < 2^266 -> top limb
    # after normalization is |h20| <= 2^6 + eps)
    hi = jnp.concatenate(
        [hi, jnp.zeros(hi.shape[:-1] + (2,), I32)], axis=-1)
    for _ in range(3):
        hi = _vpass(hi, fold=None)                  # internal, no wrap
    # product === lo + 608*HI; HI's limb 20 sits at 2^260 === 608, so it
    # contributes 608*608*h20 to limb 0 (|.| <= 2^25)
    r = lo + FOLD * hi[..., :NLIMBS]
    r = r.at[..., 0].add((FOLD * FOLD) * hi[..., NLIMBS])
    return carry(r)


def sqr(a: jnp.ndarray) -> jnp.ndarray:
    return mul(a, a)


def mul_small(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """Multiply by a small constant (k < 2^17)."""
    return carry(a * jnp.asarray(k, I32))


def one_like(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.zeros_like(a).at[..., 0].set(1)


def pow_p(a: jnp.ndarray, e: int) -> jnp.ndarray:
    """a^e by left-to-right square-and-multiply over the static exponent
    bits, as a `lax.scan` — a 255-squaring chain unrolled into the graph
    compiles in O(minutes) on XLA, so the loop must be rolled (one body
    compile, sequential execution; the batch axis keeps the VPU fed)."""
    bits = jnp.asarray([(e >> i) & 1 for i in
                        reversed(range(e.bit_length()))], bool)

    def body(r, bit):
        r = sqr(r)
        return jnp.where(bit, mul(r, a), r), None

    r, _ = jax.lax.scan(body, one_like(a), bits)
    return r


def inv(a: jnp.ndarray) -> jnp.ndarray:
    return pow_p(a, P - 2)


def freeze(a: jnp.ndarray) -> jnp.ndarray:
    """Canonical representative in [0, p) with strict limbs.

    Signed-limb values can be negative, so 64p (the SUB_K spread — its
    oversized top limb is fine here, strict_carry eats it) is added
    first: the total becomes positive, and strict normalization then
    leaves a value < 2^260 < 33p for the branch-free conditional
    subtraction ladder."""
    a = strict_carry(a + SUB_K)
    for m in (16, 8, 4, 2, 1, 1):
        mp = to_limbs(m * P)
        ge = _geq(a, mp)
        a = jnp.where(ge[..., None], _raw_sub(a, mp), a)
    return a


def _raw_sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a - b for a >= b, both limb-normalized: signed chain, no fold.
    Generic over the limb count (also used for mod-L scalars)."""
    return _carry_chain(a - b)[0]


def _geq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a >= b on normalized limbs (lexicographic from the top).
    Generic over the limb count."""
    gt = jnp.zeros(a.shape[:-1], bool)
    eq = jnp.ones(a.shape[:-1], bool)
    for k in reversed(range(a.shape[-1])):
        ak, bk = a[..., k], b[..., k]
        gt = gt | (eq & (ak > bk))
        eq = eq & (ak == bk)
    return gt | eq


def eq_mod_p(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a == b (mod p) for partially-reduced inputs."""
    fa, fb = freeze(a), freeze(b)
    return jnp.all(fa == fb, axis=-1)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(freeze(a) == 0, axis=-1)
