"""Pallas TPU kernels for Ed25519 verification — the north-star path.

Why a kernel at all: the XLA-level verifier dispatches ~600 small ops
per double-scalar-mult bit, each round-tripping its [B, 20] intermediate
through HBM; measured on a v5e that caps batched verification at ~28k
sigs/sec regardless of batch size.  These kernels run the *entire*
sequential loop (260 Straus steps, or ~253 pow steps) inside one
`pallas_call`: every limb array lives in VMEM/registers for the whole
loop, so the only HBM traffic is the kernel's inputs and outputs.

Layout: limbs on sublanes, batch lanes last — field elements are
[20, B_TILE] int32 tiles (B_TILE a multiple of 128), so every limb op
is an 8x128-aligned VPU op and limb shifts are sublane concatenations.
The grid walks batch tiles; each grid step is an independent slice of
the batch.

The in-kernel field arithmetic mirrors crypto/field_jax.py (same
radix-2^13 signed-limb scheme, same bounds — see that module's
docstring); differential tests drive both against the RFC 8032 oracle.
CPU correctness tests run the same kernels under `interpret=True`.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from agnes_tpu.crypto import ed25519_ref as ref
from agnes_tpu.crypto.field_jax import BITS, FOLD, LMASK, NLIMBS, P, I32

B_TILE = 512              # batch lanes per grid step (multiple of 128)
N_BITS = 260              # scalar bits walked by the Straus loop

# curve constants in limbs-first layout helpers ------------------------------


def _const_limbs(x: int) -> np.ndarray:
    return np.asarray([(x >> (BITS * i)) & LMASK for i in range(NLIMBS)],
                      np.int32)


_D2 = _const_limbs(2 * ref.D % P)


# --- in-kernel field ops ([20, B] int32, limbs on axis 0) -------------------


def _vpass0(r, fold):
    """One vectorized carry pass along the limb (sublane) axis.
    fold=None: exact, top limb intact.  Same math/bounds as
    field_jax._vpass (batch-last variant)."""
    lo = r & LMASK
    hi = r >> BITS
    if fold is None:
        lo = jnp.concatenate([lo[:-1], r[-1:]], axis=0)
        shift = jnp.concatenate([jnp.zeros_like(hi[:1]), hi[:-1]], axis=0)
        return lo + shift
    shift = jnp.concatenate([hi[-1:] * fold, hi[:-1]], axis=0)
    return lo + shift


def _carry0(r, passes=4):
    for _ in range(passes):
        r = _vpass0(r, FOLD)
    return r


def _fe_add(a, b):
    return _carry0(a + b, passes=2)


def _fe_sub(a, b):
    return _carry0(a - b, passes=2)


def _shift_rows(term, i):
    """[20, B] -> [40, B] with `term` placed at rows [i, i+20) — pad
    with zero rows (Mosaic has no scatter; pad/concat lowers fine)."""
    return jnp.pad(term, ((i, NLIMBS - i), (0, 0)))


def _fe_mul(a, b):
    """[20, B] x [20, B] -> [20, B], weak limbs.  Schoolbook as 20
    shifted multiply-adds into a 40-row column accumulator; row 39
    stays zero and serves as the exact-mode top for the high half."""
    cols = _shift_rows(a[0:1] * b, 0)
    for i in range(1, NLIMBS):
        cols = cols + _shift_rows(a[i:i + 1] * b, i)
    lo, hi = cols[:NLIMBS], cols[NLIMBS:]
    for _ in range(3):
        hi = _vpass0(hi, None)
    return _carry0(lo + FOLD * hi)


def _fe_mul_const(a, c_limbs):
    """[20, B] times a compile-time constant (a limb list)."""
    cols = None
    for i in range(NLIMBS):
        ci = int(c_limbs[i])
        if ci:
            term = _shift_rows(ci * a, i)
            cols = term if cols is None else cols + term
    lo, hi = cols[:NLIMBS], cols[NLIMBS:]
    for _ in range(3):
        hi = _vpass0(hi, None)
    return _carry0(lo + FOLD * hi)


Point0 = Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]


def _pt_add(p: Point0, q: Point0) -> Point0:
    """Unified a=-1 twisted Edwards addition (complete), 9 muls."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = _fe_mul(_fe_sub(y1, x1), _fe_sub(y2, x2))
    b = _fe_mul(_fe_add(y1, x1), _fe_add(y2, x2))
    c = _fe_mul_const(_fe_mul(t1, t2), _D2)
    zz = _fe_mul(z1, z2)
    d = _carry0(2 * zz, passes=2)
    e, f = _fe_sub(b, a), _fe_sub(d, c)
    g, h = _fe_add(d, c), _fe_add(b, a)
    return (_fe_mul(e, f), _fe_mul(g, h), _fe_mul(f, g), _fe_mul(e, h))


def _fe_one(B: int) -> jnp.ndarray:
    row = jax.lax.broadcasted_iota(I32, (NLIMBS, B), 0)
    return jnp.where(row == 0, 1, 0).astype(I32)


def _identity0(B: int) -> Point0:
    zero = jnp.zeros((NLIMBS, B), I32)
    one = _fe_one(B)
    return (zero, one, one, zero)


# --- the Straus kernel ------------------------------------------------------


def _straus_kernel(table_ref, sel_ref, out_ref):
    """table [4, 4, 20, Bt] (point, coord, limb, lane): the branch-free
    addend table {identity, B, -A, B-A}; sel [N_BITS, Bt] in 0..3
    (MSB-first bs + 2*bk); out [4, 20, Bt] = [s]B - [k]A."""
    B = table_ref.shape[-1]
    table = [[table_ref[p, c] for c in range(4)] for p in range(4)]

    def body(i, acc):
        acc = _pt_add(acc, acc)
        sel = sel_ref[pl.ds(i, 1), :]          # [1, B]
        pick = []
        for c in range(4):
            v = table[0][c]
            for j in (1, 2, 3):
                v = jnp.where(sel == j, table[j][c], v)
            pick.append(v)
        return _pt_add(acc, tuple(pick))

    acc = jax.lax.fori_loop(0, N_BITS, body, _identity0(B))
    for c in range(4):
        out_ref[c] = acc[c]


def _pow_kernel(n_bits: int, bits_ref, x_ref, out_ref):
    """out = x ** e; the exponent bit string (MSB first) arrives lane-
    replicated as [n_bits, B] (Mosaic cannot broadcast along sublanes
    and lanes at once, so the lane axis is materialized on the host) —
    square-and-multiply with branch-free select."""
    B = x_ref.shape[-1]
    x = x_ref[:]

    def body(i, r):
        r = _fe_mul(r, r)
        bit = bits_ref[pl.ds(i, 1), :]                     # [1, B]
        return jnp.where(bit > 0, _fe_mul(r, x), r)

    out_ref[:] = jax.lax.fori_loop(0, n_bits, body, _fe_one(B))


# --- host-facing wrappers ---------------------------------------------------


def _pad_to_tile(x_bl: jnp.ndarray, b_tile: int) -> Tuple[jnp.ndarray, int]:
    """[B, ...] -> [B_pad, ...] with B_pad a multiple of b_tile."""
    B = x_bl.shape[0]
    B_pad = -(-B // b_tile) * b_tile
    if B_pad != B:
        pad = [(0, B_pad - B)] + [(0, 0)] * (x_bl.ndim - 1)
        x_bl = jnp.pad(x_bl, pad)
    return x_bl, B


def straus_sub_pallas(s_limbs: jnp.ndarray, k_limbs: jnp.ndarray,
                      a_point, interpret: bool = False,
                      b_tile: int = B_TILE):
    """Drop-in for ed25519_jax.straus_sub: [s]B - [k]A via the Pallas
    kernel.  s_limbs/k_limbs [B, 20]; a_point an ed25519_jax.Point of
    [B, 20] leaves.  Returns an ed25519_jax.Point."""
    from agnes_tpu.crypto import ed25519_jax as E
    from agnes_tpu.crypto import scalar_jax as S

    shape = s_limbs.shape[:-1]
    na = E.point_neg(a_point)
    b = E.base_point(shape)
    bma = E.point_add(b, na)
    idn = E.identity(shape)
    # [4 points, 4 coords, B, 20] -> [4, 4, 20, B]
    table = jnp.stack([jnp.stack(list(p)) for p in (idn, b, na, bma)])
    table = jnp.moveaxis(table, -1, -2)

    sbits = S.bits_msb_first(s_limbs)          # [260, B] bool
    kbits = S.bits_msb_first(k_limbs)
    sel = sbits.astype(I32) + 2 * kbits.astype(I32)

    table_t, B = _pad_to_tile(jnp.moveaxis(table, -1, 0), b_tile)
    table_t = jnp.moveaxis(table_t, 0, -1)                 # [4,4,20,Bp]
    sel_t, _ = _pad_to_tile(jnp.moveaxis(sel, -1, 0), b_tile)
    sel_t = jnp.moveaxis(sel_t, 0, -1)                     # [260,Bp]
    B_pad = table_t.shape[-1]

    out = pl.pallas_call(
        _straus_kernel,
        grid=(B_pad // b_tile,),
        in_specs=[
            pl.BlockSpec((4, 4, NLIMBS, b_tile),
                         lambda g: (0, 0, 0, g),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((N_BITS, b_tile), lambda g: (0, g),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((4, NLIMBS, b_tile), lambda g: (0, 0, g),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((4, NLIMBS, B_pad), jnp.int32),
        interpret=interpret,
    )(table_t, sel_t)

    coords = [jnp.moveaxis(out[c], 0, -1)[:B] for c in range(4)]
    return E.Point(*coords)


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def _pow_pallas_impl(x_limbs, e: int, interpret: bool, b_tile: int):
    bits = np.asarray([(e >> i) & 1 for i in
                       reversed(range(e.bit_length()))], np.int32)
    x_t, B = _pad_to_tile(x_limbs, b_tile)     # [Bp, 20]
    x_t = jnp.moveaxis(x_t, 0, -1)             # [20, Bp]
    B_pad = x_t.shape[-1]
    bits_arr = jnp.broadcast_to(jnp.asarray(bits)[:, None],
                                (len(bits), b_tile))
    out = pl.pallas_call(
        functools.partial(_pow_kernel, len(bits)),
        grid=(B_pad // b_tile,),
        in_specs=[
            pl.BlockSpec((len(bits), b_tile), lambda g: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((NLIMBS, b_tile), lambda g: (0, g),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((NLIMBS, b_tile), lambda g: (0, g),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((NLIMBS, B_pad), jnp.int32),
        interpret=interpret,
    )(bits_arr, x_t)
    return jnp.moveaxis(out, 0, -1)[:B]


def pow_p_pallas(x_limbs: jnp.ndarray, e: int, interpret: bool = False,
                 b_tile: int = B_TILE) -> jnp.ndarray:
    """Drop-in for field_jax.pow_p ([B, 20] layout)."""
    return _pow_pallas_impl(x_limbs, e, interpret, b_tile)


from agnes_tpu.device import registry as _registry  # noqa: E402

_registry.register(_registry.EntrySpec(
    name="pallas_pow_p", fn=_pow_pallas_impl, jit=_pow_pallas_impl,
    statics=("e", "interpret", "b_tile"), hot=False,
    pallas_backends=("tpu", "interpret")))
