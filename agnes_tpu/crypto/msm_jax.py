"""MSM-style Ed25519 batch verification (random linear combination).

The SURVEY §7 "hard parts" mitigation and BASELINE north-star
mechanism: instead of checking [Sᵢ]B == Rᵢ + [kᵢ]Aᵢ per lane, draw
random 128-bit zᵢ and check ONE combined equation

    [Σ zᵢSᵢ mod L]B  +  Σ [zᵢ](-Rᵢ)  +  Σ [zᵢkᵢ mod L](-Aᵢ)  ==  O

(Bernstein et al.'s batch verification).  A lane that fails its own
equation makes the combination nonzero except with probability
~2⁻¹²⁸; a passing batch certifies every (structurally valid) lane.

Why this is fast on TPU: per-lane double-scalar multiplication costs
~253 doublings *per signature*.  Here the two big multi-scalar
multiplications are done with a Pippenger bucket method whose serial
doubling chain is shared by the WHOLE batch (c-bit windows, bucket
accumulation per window, c doublings per window to combine), so the
per-signature cost collapses to ~2 bucket additions per window
(2·(253+128)/c adds total).  The bucket accumulation itself is
expressed as a *segmented* `jax.lax.associative_scan` over the batch
sorted by digit — sorting makes equal digits adjacent, the segmented
combine sums each digit's run, and the scan is log-depth and fully
vectorized: a TPU-idiomatic Pippenger with no scatter-adds and no
data-dependent shapes.

Agreement with the per-lane verifiers: the framework's verification
policy is COFACTORED everywhere (rationale: ed25519_ref.verify) — the
per-lane verifiers check [8]([S]B - [k]A) == [8]R, and this batch
check multiplies the combined equation by 8 as well.  A torsion-only
per-lane defect is therefore accepted by BOTH strategies (never by
one and not the other), and a non-torsion defect fails the batch
equation except with probability ~2⁻¹²⁸: batch-accept and per-lane
accept provably agree, so vote validity stays a pure function of the
signature bytes no matter which strategy a node uses.
`verify_batch_adaptive` uses the batch check as the honest-stream
fast path and bisects to the per-lane verifier to localize bad lanes
when it fails.

The reference engine has no crypto at all (votes are unsigned,
SURVEY.md §2.1; signing stubbed at reference consensus_executor.rs:
35-41); this module is part of the added TPU data plane.

MEASURED ROLE (r4, TPU v5e): the log-depth formulation does NOT win
on real hardware — the segmented scan costs O(N log N) lane
point-adds (log₂N levels per window × 33 windows ≈ 460 full-lane
adds at N=16k, about the same add count as per-lane Straus' ~390)
plus 33 argsort+gather rounds, which the TPU memory system hates:
15.4k verifies/s vs the fused per-lane kernel's 1.41M/s
(scripts/profile_verify.py).  The per-lane Pallas kernel
(pallas_verify.py) is therefore the production path on TPU;
this module remains the amortized-soundness ALTERNATIVE (one
combined equation certifying a whole batch — a property the
per-lane path cannot offer) and the cross-check oracle in
tests/test_cofactored.py.
"""

from __future__ import annotations

import secrets
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from agnes_tpu.crypto import ed25519_jax as E
from agnes_tpu.crypto import field_jax as F
from agnes_tpu.crypto import scalar_jax as S
from agnes_tpu.crypto import sha512_jax as sha

I32 = F.I32
BITS = F.BITS

Z_BITS = 128                     # random-coefficient width
Z_LIMBS = -(-Z_BITS // BITS)     # 10
WINDOW_C = 8                     # Pippenger window (bits)
N_BUCKETS = 1 << WINDOW_C
NW_Z = -(-Z_BITS // WINDOW_C)            # 16 windows for z scalars
NW_FULL = -(-253 // WINDOW_C)            # 32 windows for full scalars


# --- scalar helpers (mod L) -------------------------------------------------


def mul_mod_L(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """[..., na] x [..., nb] limb products mod L -> [..., 20] canonical.

    Raw schoolbook columns stay int32-safe: limbs < 2^13, products
    < 2^26, <= min(na, nb) <= 20 terms per column < 2^31."""
    na, nb = a.shape[-1], b.shape[-1]
    assert na <= 20 and nb <= 20
    cols = jnp.zeros(a.shape[:-1] + (na + nb - 1,), I32)
    for i in range(na):
        cols = cols.at[..., i:i + nb].add(a[..., i:i + 1] * b)
    limbs = S._chain(cols)                       # normalized, +1 limb
    pad = S.N_HASH - limbs.shape[-1]
    limbs = jnp.pad(limbs, [(0, 0)] * (limbs.ndim - 1) + [(0, pad)])
    return S.barrett_reduce(limbs)


def sum_mod_L(x: jnp.ndarray) -> jnp.ndarray:
    """[B, 20] canonical scalars -> [20] limbs of the sum mod L.

    Pure int32 (jnp.int64 silently downcasts without x64 mode):
    chunked partial sums stay < 2^28 per column, are normalized, and
    the <= B/2^15 normalized partials sum safely again — int32-exact
    for B up to ~2^33 lanes."""
    chunk = 1 << 15
    B = x.shape[0]
    pad_b = (-B) % chunk
    xp = jnp.pad(x, ((0, pad_b), (0, 0)))
    parts = xp.reshape(-1, chunk, x.shape[-1]).sum(axis=1)   # [m, n]
    parts = S._chain(parts)                                  # normalized
    tot = parts.sum(axis=0)                                  # < m * 2^28
    limbs = S._chain(S._chain(tot[None]))[0]
    pad = S.N_HASH - limbs.shape[-1]
    return S.barrett_reduce(jnp.pad(limbs, [(0, pad)]))


def window_digits(s: jnp.ndarray, n_windows: int,
                  c: int = WINDOW_C, bits: int = BITS) -> jnp.ndarray:
    """[..., n_limbs] limbs -> [n_windows, ...] c-bit digits, least
    significant window first.

    Generalized (ISSUE 10 satellite): `c` is the window width and
    `bits` the scalar's limb radix — the Ed25519 instantiation is the
    default (c=8 over 13-bit limbs), the BLS lane reads 4-bit windows
    over `bls_field_jax`'s 12-bit limbs.  A window may straddle at
    most one limb boundary, so c <= bits is required."""
    assert 0 < c <= bits, (c, bits)
    nl = s.shape[-1]
    outs = []
    for w in range(n_windows):
        lo = c * w
        li, off = lo // bits, lo % bits
        d = s[..., li] >> off
        if off > bits - c and li + 1 < nl:
            d = d | (s[..., li + 1] << (bits - off))
        outs.append(d & ((1 << c) - 1))
    return jnp.stack(outs, axis=0)


# --- segmented-scan Pippenger MSM -------------------------------------------


def _seg_combine(a, b):
    """Segmented-scan operator: flags mark segment starts; a right
    element that starts a segment resets the running point sum."""
    fa, pa = a
    fb, pb = b
    psum = E.point_add(E.Point(*pa), E.Point(*pb))
    keep = fb[..., None]
    out = tuple(jnp.where(keep, qb, qs)
                for qb, qs in zip(pb, tuple(psum)))
    return fa | fb, out


def _bucket_sums(points: E.Point, digits: jnp.ndarray) -> E.Point:
    """One window's bucket sums: [N]-lane points + [N] digits ->
    [N_BUCKETS]-lane points where lane d = Σ points with digit d
    (identity where empty).  Sort-by-digit + segmented scan."""
    n = digits.shape[0]
    order = jnp.argsort(digits)                  # stable
    ds = digits[order]
    pts = tuple(coord[order] for coord in points)
    seg_start = jnp.concatenate(
        [jnp.ones((1,), bool), ds[1:] != ds[:-1]])
    _, scanned = jax.lax.associative_scan(
        _seg_combine, (seg_start, pts), axis=0)
    seg_end = jnp.concatenate(
        [ds[1:] != ds[:-1], jnp.ones((1,), bool)])
    # scatter each segment total into its bucket; non-end lanes go to
    # a dump slot (bucket arrays are [N_BUCKETS + 1])
    idx = jnp.where(seg_end, ds, N_BUCKETS)
    idn = E.identity((N_BUCKETS + 1,))
    buckets = tuple(
        ib.at[idx].set(sc) for ib, sc in zip(tuple(idn), scanned))
    return E.Point(*tuple(b[:N_BUCKETS] for b in buckets))


def bucket_aggregate_generic(buckets, *, point_add, identity,
                             n_buckets: int):
    """Σ_{d=1}^{n_buckets-1} d * bucket[d] via the running-suffix
    trick (acc accumulates suffix sums, total accumulates acc) —
    curve-generic: `point_add` combines two point pytrees, `identity`
    builds an identity of a given leading shape.  The loop is a rolled
    `fori_loop`, so the traced graph holds TWO point-add bodies
    however wide the window is."""
    idn = identity(())

    def body(j, carry):
        acc, tot = carry
        d = n_buckets - 1 - j
        bd = jax.tree.map(lambda c: c[d], buckets)
        acc = point_add(acc, bd)
        tot = point_add(tot, acc)
        return acc, tot

    _, tot = jax.lax.fori_loop(0, n_buckets - 1, body, (idn, idn))
    return tot


def _bucket_aggregate(buckets: E.Point) -> E.Point:
    """The Ed25519 instantiation of `bucket_aggregate_generic`."""
    return bucket_aggregate_generic(
        buckets, point_add=E.point_add, identity=E.identity,
        n_buckets=N_BUCKETS)


def bucket_sums_seq(points, digits: jnp.ndarray, *, point_add,
                    identity, n_buckets: int):
    """One window's bucket sums, curve-generic, with the segmented
    accumulation as a SEQUENTIAL `lax.scan` over the sorted lanes
    instead of the log-depth associative scan: the traced graph holds
    ONE point-add body regardless of N.

    That trade is deliberate for the BLS lane: a generic-prime
    (Barrett) field add costs ~5-15k traced ops, so the associative
    scan's log2(N) instantiations would blow the XLA graph past
    practical compile budgets, while the per-class lane counts
    (N <= 1024) make N sequential adds cheap at runtime.  Ed25519's
    `_bucket_sums` keeps the log-depth formulation (its field is ~10x
    cheaper to instantiate and its batch sizes 100x larger).

    Kernel lane (ISSUE 18): the BLS `point_add` closure bottoms out
    in `bls_field_jax.fv_mul_pairs`/`reduce_cols`, so under an active
    `field_backend` (the `pallas_field=` knob on `bls_aggregate`) the
    ONE point-add body this scan instantiates is the fused
    `crypto/pallas_field.py` kernel — the sequential-scan trade above
    gets cheaper still (one fused kernel, not one 5-15k-op soup)."""
    order = jnp.argsort(digits)                  # stable
    ds = digits[order]
    pts = jax.tree.map(lambda c: c[order], points)
    seg_start = jnp.concatenate(
        [jnp.ones((1,), bool), ds[1:] != ds[:-1]])
    seg_end = jnp.concatenate(
        [ds[1:] != ds[:-1], jnp.ones((1,), bool)])
    # bucket arrays are [n_buckets + 1]: non-end lanes park their
    # running sum in the dump slot (same trick as _bucket_sums)
    buckets0 = identity((n_buckets + 1,))

    def body(carry, inp):
        buckets, acc = carry
        pt, d, start, end = inp
        summed = point_add(acc, pt)
        acc = jax.tree.map(lambda a, b: jnp.where(start, a, b),
                           pt, summed)
        idx = jnp.where(end, d, n_buckets)
        buckets = jax.tree.map(lambda b, a: b.at[idx].set(a),
                               buckets, acc)
        return (buckets, acc), None

    (buckets, _), _ = jax.lax.scan(
        body, (buckets0, identity(())), (pts, ds, seg_start, seg_end))
    return jax.tree.map(lambda b: b[:n_buckets], buckets)


def bucket_aggregate_merged(buckets, *, point_add, identity,
                            n_buckets: int):
    """`bucket_aggregate_generic` with the two adds per iteration
    folded into ONE point-add instantiation (2(nb-1) iterations
    alternating acc-accumulate / total-accumulate via selects).  The
    BLS lane uses this: its generic-prime point add costs thousands of
    traced ops, so halving the instantiation count is worth the extra
    rolled iterations; Ed25519's `_bucket_aggregate` keeps the plain
    two-add body."""
    idn = identity(())

    def body(j, carry):
        acc, tot = carry
        even = (j % 2) == 0
        d = n_buckets - 1 - j // 2
        bd = jax.tree.map(lambda c: c[d], buckets)
        lhs = jax.tree.map(lambda a, t: jnp.where(even, a, t),
                           acc, tot)
        rhs = jax.tree.map(lambda b, a: jnp.where(even, b, a),
                           bd, acc)
        s = point_add(lhs, rhs)
        acc = jax.tree.map(lambda a, sv: jnp.where(even, sv, a),
                           acc, s)
        tot = jax.tree.map(lambda t, sv: jnp.where(even, t, sv),
                           tot, s)
        return acc, tot

    _, tot = jax.lax.fori_loop(0, 2 * (n_buckets - 1), body,
                               (idn, idn))
    return tot


def msm_generic(points, scalars: jnp.ndarray, n_windows: int, *,
                point_add, identity, window_c: int = WINDOW_C,
                bits: int = BITS):
    """Multi-scalar multiplication Σ [scalarᵢ] Pᵢ, generic over the
    curve (`point_add`/`identity` pytree ops), the window width and
    the scalar limb radix — the Pippenger machinery `msm` instantiates
    for Ed25519, reusable by the BLS lane (bls_jax).  Lanes with
    scalar 0 contribute nothing (every window digit lands in the
    excluded 0 bucket), which is how padding rows are dropped without
    a mask.

    Graph-size discipline: the whole MSM instantiates THREE point-add
    bodies — the sequential bucket scan, the merged bucket aggregate,
    and one (window_c + 1)-iteration fori whose first window_c
    rounds double the accumulator and whose last round adds the
    window sum (select on the iteration index)."""
    digits = window_digits(scalars, n_windows, c=window_c, bits=bits)
    nb = 1 << window_c

    def body(acc, dig):
        wsum = bucket_aggregate_merged(
            bucket_sums_seq(points, dig, point_add=point_add,
                            identity=identity, n_buckets=nb),
            point_add=point_add, identity=identity, n_buckets=nb)

        def dbl_or_add(i, a):
            rhs = jax.tree.map(
                lambda av, wv: jnp.where(i < window_c, av, wv),
                a, wsum)
            return point_add(a, rhs)

        acc = jax.lax.fori_loop(0, window_c + 1, dbl_or_add, acc)
        return acc, None

    acc, _ = jax.lax.scan(body, identity(()), digits[::-1])
    return acc


def msm(points: E.Point, scalars: jnp.ndarray,
        n_windows: int) -> E.Point:
    """Multi-scalar multiplication Σ [scalarᵢ] Pᵢ.

    points: Point with [N, 20]-limb coords; scalars [N, n_limbs];
    n_windows c-bit windows cover the scalar width.  The doubling
    chain (c per window) is shared by all N points — the Pippenger
    amortization that beats per-lane Straus for large N.  One
    `lax.scan` over windows (MSB window first) keeps the traced graph
    a single window body: acc <- [2^c] acc + Σ_d d * bucket_d."""
    digits = window_digits(scalars, n_windows)   # [n_windows, N]

    def body(acc: E.Point, dig):
        for _ in range(WINDOW_C):
            acc = E.point_add(acc, acc)
        wsum = _bucket_aggregate(_bucket_sums(points, dig))
        return E.point_add(acc, wsum), None

    acc, _ = jax.lax.scan(body, E.identity(()), digits[::-1])
    return acc


# --- the batch check --------------------------------------------------------


def scalar_mul_base(c_limbs: jnp.ndarray) -> E.Point:
    """[c]B for one scalar ([20] limbs) — reuses the Straus scan with
    the A term pinned to the identity."""
    return E.straus_sub(c_limbs, jnp.zeros_like(c_limbs), E.identity(()))


def make_z(batch: int, seed: Optional[int] = None) -> jnp.ndarray:
    """[B, Z_LIMBS] random 128-bit coefficients.  Drawn host-side per
    call from OS entropy (`secrets.token_bytes`), so the 2⁻¹²⁸
    soundness bound of the random-linear-combination check rests only
    on the CSPRNG, not on PCG64 indistinguishability.  A fixed seed
    (tests only) switches to a deterministic numpy stream.

    Vectorized repack: a 13-bit limb spans at most two adjacent
    16-bit words, so limb i is a shift of the 32-bit window at word
    (13i)//16 — no per-element Python on the verify hot path."""
    if seed is None:
        raw = np.frombuffer(secrets.token_bytes(batch * 16), dtype="<u2")
        words16 = raw.reshape(batch, 8).astype(np.int64)
    else:
        rng = np.random.default_rng(seed)
        words16 = rng.integers(0, 1 << 16, size=(batch, 8), dtype=np.int64)
    # zero pad word for the 32-bit window at the top limb
    words = np.concatenate(
        [words16, np.zeros((batch, 1), dtype=np.int64)], axis=1)
    idx = np.arange(Z_LIMBS)
    wi, off = (BITS * idx) // 16, (BITS * idx) % 16
    win = words[:, wi] | (words[:, wi + 1] << 16)
    val = (win >> off) & F.LMASK
    return jnp.asarray(val, I32)


def verify_batch_msm(pub: jnp.ndarray, sig: jnp.ndarray,
                     msg_blocks: jnp.ndarray, z: jnp.ndarray
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One combined check for the whole batch.

    pub [B,32] / sig [B,64] byte-valued int arrays, msg_blocks
    [B,n,32] uint32 (R||A||M pre-padded SHA-512 blocks), z [B,
    Z_LIMBS] random coefficients.

    Returns (batch_ok scalar bool, lane_ok [B] bool):
      batch_ok  — the combined equation holds for every lane with
                  lane_ok True (structurally invalid lanes are
                  excluded by zeroing their coefficient);
      lane_ok   — per-lane structural validity (A and R decode, S
                  canonical).  Final verdict = batch_ok ? lane_ok :
                  fallback to the per-lane verifier."""
    a_point, ok_a = E.decompress(pub)
    r_point, ok_r = E.decompress(sig[..., :32])
    s = S.scalar_from_bytes32(sig[..., 32:])
    ok_s = S.is_canonical(s)
    lane_ok = ok_a & ok_r & ok_s

    k = S.barrett_reduce(S.digest_to_limbs(sha.sha512_blocks(msg_blocks)))
    z = jnp.where(lane_ok[..., None], z, 0)      # exclude invalid lanes
    zk = mul_mod_L(z, k)                         # [B, 20]
    zs = mul_mod_L(z, s)
    c = sum_mod_L(zs)                            # [20]

    t = E.point_add(
        scalar_mul_base(c),
        E.point_add(msm(E.point_neg(r_point), z, NW_Z),
                    msm(E.point_neg(a_point), zk, NW_FULL)))
    for _ in range(3):                   # x8: cofactored policy
        t = E.point_add(t, t)
    batch_ok = E.point_equal(t, E.identity(()))
    return batch_ok, lane_ok


verify_batch_msm_jit = jax.jit(verify_batch_msm)

from agnes_tpu.device import registry as _registry  # noqa: E402

_registry.register(_registry.EntrySpec(
    name="verify_batch_msm", fn=verify_batch_msm,
    jit=verify_batch_msm_jit, hot=False))


def _pad_pow2(arr: jnp.ndarray, n: int) -> jnp.ndarray:
    return jnp.pad(arr, [(0, n - arr.shape[0])]
                   + [(0, 0)] * (arr.ndim - 1))


def verify_batch_adaptive(pub: jnp.ndarray, sig: jnp.ndarray,
                          msg_blocks: jnp.ndarray,
                          seed: Optional[int] = None,
                          leaf: int = 64) -> np.ndarray:
    """[B] bool verdicts with per-lane-identical semantics (the
    cofactored policy holds on both paths): try the MSM fast path; on
    failure bisect, settling sub-batches smaller than `leaf` with the
    per-lane verifier.  An all-honest batch costs one MSM pass; an
    adversary injecting bad lanes only pushes those sub-batches onto
    the per-lane path.

    Sub-batches are padded to the next power of two before the MSM
    call (pad lanes get z = 0, contributing nothing) so the jit cache
    holds O(log B) shapes — otherwise adversarial bisection at
    varying tick sizes would force a fresh XLA compile per size, a
    cheap unauthenticated latency-amplification vector."""
    B = int(pub.shape[0])
    out = np.zeros(B, bool)
    # leaf >= 2: at leaf 1 the bisection midpoint lo + n//2 == lo and
    # a failing lane would recurse forever
    leaf = max(int(leaf), 2)

    def solve(lo: int, hi: int) -> None:
        n = hi - lo
        if n == 0:
            return
        if n < leaf:
            # pad to the fixed leaf size: one per-lane compile shape
            out[lo:hi] = np.asarray(E.verify_batch_jit(
                _pad_pow2(pub[lo:hi], leaf), _pad_pow2(sig[lo:hi], leaf),
                _pad_pow2(msg_blocks[lo:hi], leaf)))[:n]
            return
        n2 = 1 << (n - 1).bit_length()
        z = _pad_pow2(make_z(n, seed), n2)
        batch_ok, lane_ok = verify_batch_msm_jit(
            _pad_pow2(pub[lo:hi], n2), _pad_pow2(sig[lo:hi], n2),
            _pad_pow2(msg_blocks[lo:hi], n2), z)
        if bool(np.asarray(batch_ok)):
            out[lo:hi] = np.asarray(lane_ok)[:n]
            return
        mid = lo + n // 2
        solve(lo, mid)
        solve(mid, hi)

    solve(0, B)
    return out
