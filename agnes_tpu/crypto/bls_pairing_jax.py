"""Device BLS12-381 pairing: optimal-ate Miller loop + final
exponentiation (ISSUE 13 tentpole).

PR 10 left the O(1)-per-class pairing on the host (~0.8-3s of pure
python per closed vote class through `bls_ref`) — the one piece of
host crypto in the aggregate lane's steady state.  This module is
that piece on device, batched so ALL deadline-closed classes clear in
one padded-rung dispatch (`bls_pairing_product`): per class, the
product  e(-G1, asig) * e(apk, H(msg)) == 1  is decided entirely in
the traced graph and only a [C] bool array crosses back to the host.

Algorithm (validated step-by-step against `bls_ref` — the repo's
derive-and-assert pattern):

* **Miller loop** over the static ate count |x| (the BLS parameter;
  the x < 0 conjugation is skipped, consistent with `bls_ref`),
  G2 points in HOMOGENEOUS projective Fp2 coordinates and G1 points
  in projective Fp — the MSM's outputs feed in directly, no host
  normalization, no device inversion.  Line evaluations are scaled
  by per-step factors in Fp2/Fp4 subfields (2YZ^2, B*Z1, Z_P, w^3),
  all of which the final exponentiation's easy part annihilates
  (every proper-subfield unit has order dividing (p^6-1)(p^2+1)).
  The loop is a ROLLED `fori_loop` over a static bit table: ONE
  doubling-step body and ONE addition-step body in the traced graph
  (the addition step runs every iteration, select-gated by the bit —
  branch-free, and the graph diet beats the ~40% runtime overhead of
  computing it on zero bits).
* **Final exponentiation** f^(3 (p^12-1)/r) — the CUBE of
  `bls_ref.final_exponentiate`'s value, via the x-is-static chain
      3H = (x-1)^2 (x+p) (x^2+p^2-1) + 3,   H = (p^4-p^2+1)/r
  (asserted at import).  Verdict-equivalent: the pairing output has
  order dividing r and gcd(3, r) = 1, so f^(3H') == 1 iff f^H' == 1
  — and the differential tests pin device == ref^3 EXACTLY.  Easy
  part pays the one Fp12 inversion (Fermat chain); the hard part is
  five x-exponentiations, each a rolled 63-iteration loop of one
  cyclotomic square + one select-gated multiply.

Degenerate inputs are REJECT-safe by construction: an identity or
wrong-subgroup point that hits an exceptional case of the projective
formulas collapses the Miller value to 0, and 0 can never final-
exponentiate to 1 — the lane falls back to the per-share host oracle
(the safe direction; soundness never rests on this module accepting).
Identity aggregates follow `bls_ref.pairing_product_is_one`'s
skip-the-pair semantics via an explicit Z == 0 (mod p) select.

Compile-budget note: the whole entry traces ~100k primitives at the
audit shape (the jaxpr census baseline pins it, ±10%) — the same
class as the `bls_aggregate` MSM — because every tower multiply
funnels through `bls_field_jax.fv_mul_pairs`' ONE stacked Barrett
body, every loop is rolled over static bit tables, and loop-carry
values reduce in one stacked call per body; without the diet the
same algorithm traced 625k primitives and never compiled inside the
ladder budget.  The remaining rung (a Pallas pairing kernel) is
named in ROADMAP.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from agnes_tpu.crypto import bls_field_jax as BF
from agnes_tpu.crypto import bls_ref as ref
from agnes_tpu.crypto import bls_tower_jax as T
from agnes_tpu.crypto.bls_field_jax import (
    FV,
    FV2,
    NLIMBS,
    RED_BOUND,
    fv2_add,
    fv2_sub,
    fv_mul_pairs,
    fv_sub,
)
from agnes_tpu.crypto.bls_tower_jax import FV12

# the positive Miller loop count and its static bit table (bits below
# the MSB, MSB first) — x is STATIC, so the loop structure is baked at
# trace time
_ATE = -ref.X_PARAM
_ATE_BITS: Tuple[int, ...] = tuple(
    (_ATE >> i) & 1 for i in range(_ATE.bit_length() - 2, -1, -1))

# the final exponentiation's hard-part identity, asserted at import
# (the bls_ref derive-and-assert pattern): 3H = (x-1)^2 (x+p)
# (x^2+p^2-1) + 3 for H = (p^4-p^2+1)/r
_P, _R, _X = ref.P, ref.R, ref.X_PARAM
assert (ref.P**4 - ref.P**2 + 1) % ref.R == 0
assert (_X - 1) ** 2 * (_X + _P) * (_X**2 + _P**2 - 1) + 3 \
    == 3 * ((_P**4 - _P**2 + 1) // _R)


def _dbl(x: FV2) -> FV2:
    return fv2_add(x, x)


def _mul3(x: FV2) -> FV2:
    return fv2_add(_dbl(x), x)


def _wrap_g2(q: jnp.ndarray, bound: int = RED_BOUND):
    """[..., 3, 2, NLIMBS] -> (X, Y, Z) FV2 triple."""
    return tuple(FV2(FV(q[..., k, 0, :], bound),
                     FV(q[..., k, 1, :], bound)) for k in range(3))


def _wrap_g1(p: jnp.ndarray, bound: int = RED_BOUND):
    """[..., 3, NLIMBS] -> (X, Y, Z) FV triple."""
    return tuple(FV(p[..., k, :], bound) for k in range(3))


def _out_g2(pt) -> jnp.ndarray:
    return jnp.stack([jnp.stack([c.c0.a, c.c1.a], axis=-2)
                      for c in pt], axis=-3)


def _mul_fp(x: FV2, s: FV) -> List[tuple]:
    """Operand pairs of the Fp2 x Fp product (two base products)."""
    return [(x.c0, s), (x.c1, s)]


def _dbl_step(R, Pp):
    """Projective doubling of R on y^2 z = x^3 + b' z^3 over Fp2 with
    the tangent line evaluated at the projective G1 point Pp,
    untwisted and uniformly scaled by w^3 * 2YZ^2 * Z_P (subfield
    factors, killed by the easy part).  Returns (2R, line) where line
    is the sparse coefficient triple (c0, c2, c3) over {1, w^2, w^3}:
      c0 = (2 Y^2 Z - 3 X^3) * Z_P
      c2 = 3 X^2 Z * X_P
      c3 = -2 Y Z^2 * Y_P."""
    X, Y, Z = R
    XP, YP, ZP = Pp
    # layer 1: the independent squares/products
    pr = fv_mul_pairs(
        T.fv2_mul_pairs_expand_many([(X, X), (Y, Y), (Z, Z), (Y, Z),
                                     (X, Y)]))
    t0, t1, t2, S, XY = T.fv2_mul_pairs_combine_many(pr, 5)
    W = _mul3(t0)
    # layer 2
    pr = fv_mul_pairs(
        T.fv2_mul_pairs_expand_many(
            [(XY, S), (W, W), (S, S), (t1, Z), (t0, X), (W, Z),
             (Y, t2)]))
    B, W2, Ssq, t1Z, t0X, WZ, Yt2 = T.fv2_mul_pairs_combine_many(pr, 7)
    H = fv2_sub(W2, _dbl(_dbl(_dbl(B))))
    # layer 3: outputs + line coefficients (Fp2 x Fp products ride the
    # same stacked call)
    pairs = T.fv2_mul_pairs_expand_many(
        [(H, S), (W, fv2_sub(_dbl(_dbl(B)), H)), (t1, Ssq), (S, Ssq)])
    c0_in = fv2_sub(_dbl(t1Z), _mul3(t0X))
    pairs += _mul_fp(c0_in, ZP) + _mul_fp(WZ, XP) + _mul_fp(Yt2, YP)
    pr = fv_mul_pairs(pairs)
    HS, Wt, t1S2, S3 = T.fv2_mul_pairs_combine_many(pr, 4)
    c0 = FV2(pr[12], pr[13])
    c2 = FV2(pr[14], pr[15])
    c3n = FV2(pr[16], pr[17])                  # -c3
    e8 = lambda v: _dbl(_dbl(_dbl(v)))         # noqa: E731
    # outputs UNREDUCED: consumers (the next multiply's stacked
    # kernel, or the body's one stacked carry reduction) handle it
    return (_dbl(HS), fv2_sub(Wt, e8(t1S2)), e8(S3)), \
        (c0, c2, _dbl(c3n))


def _add_step(R, Q, Pp):
    """Projective addition R + Q with the chord line through them
    evaluated at Pp, scaled by B * Z1 * Z_P (subfield factors):
      A = Y2 Z1 - Y1 Z2,  B = X2 Z1 - X1 Z2
      c0 = (Y1 B - A X1) * Z_P ; c2 = A Z1 * X_P ; c3 = -B Z1 * Y_P."""
    X1, Y1, Z1 = R
    X2, Y2, Z2 = Q
    XP, YP, ZP = Pp
    pr = fv_mul_pairs(T.fv2_mul_pairs_expand_many(
        [(Y2, Z1), (Y1, Z2), (X2, Z1), (X1, Z2), (Z1, Z2)]))
    Y2Z1, Y1Z2, X2Z1, X1Z2, Z1Z2 = T.fv2_mul_pairs_combine_many(pr, 5)
    A = fv2_sub(Y2Z1, Y1Z2)
    B = fv2_sub(X2Z1, X1Z2)
    pr = fv_mul_pairs(T.fv2_mul_pairs_expand_many(
        [(B, B), (A, A), (Y1, B), (A, X1), (A, Z1), (B, Z1)]))
    B2, A2, Y1B, AX1, AZ1, BZ1 = T.fv2_mul_pairs_combine_many(pr, 6)
    pr = fv_mul_pairs(T.fv2_mul_pairs_expand_many(
        [(B2, B), (B2, X1Z2), (A2, Z1Z2)]))
    B3, vX1Z2, u2Z = T.fv2_mul_pairs_combine_many(pr, 3)
    Wn = fv2_sub(fv2_sub(u2Z, B3), _dbl(vX1Z2))
    pairs = T.fv2_mul_pairs_expand_many(
        [(B, Wn), (A, fv2_sub(vX1Z2, Wn)), (B3, Y1Z2), (B3, Z1Z2)])
    pairs += (_mul_fp(fv2_sub(Y1B, AX1), ZP) + _mul_fp(AZ1, XP)
              + _mul_fp(BZ1, YP))
    pr = fv_mul_pairs(pairs)
    X3, Yt, B3Y, Z3 = T.fv2_mul_pairs_combine_many(pr, 4)
    c0 = FV2(pr[12], pr[13])
    c2 = FV2(pr[14], pr[15])
    c3n = FV2(pr[16], pr[17])
    return (X3, fv2_sub(Yt, B3Y), Z3), (c0, c2, c3n)


def _mul_line(f: FV12, line) -> FV12:
    """f * (c0 + c2 w^2 + c3 w^3) with c3 carried NEGATED (the line
    builders emit -c3 to spare a negation) — a full Karatsuba Fp12
    multiply against the padded sparse element: one more stacked body
    would not pay for the sparse special-case here (the diet trades
    graph size first)."""
    c0, c2, c3n = line
    zero = FV2(FV(jnp.zeros_like(c0.c0.a), 1),
               FV(jnp.zeros_like(c0.c0.a), 1))
    neg3 = FV2(fv_sub(FV(jnp.zeros_like(c3n.c0.a), 1), c3n.c0),
               fv_sub(FV(jnp.zeros_like(c3n.c1.a), 1), c3n.c1))
    ln = FV12((c0, zero, c2, neg3, zero, zero))
    return T.fv12_mul(f, ln)


_red12 = T.fv12_force_red


def miller_loop(q_pts: jnp.ndarray, p_pts: jnp.ndarray) -> FV12:
    """Batched optimal-ate Miller loop: q_pts [..., 3, 2, NLIMBS]
    projective G2 (the twist), p_pts [..., 3, NLIMBS] projective G1.
    Returns the Miller value as an FV12 (equal to `bls_ref`'s affine
    miller_loop up to subfield factors — compare after the final
    exponentiation).  One rolled loop: doubling step every iteration,
    addition step select-gated by the static ate bit table; the whole
    body's carry values reduce in ONE stacked Barrett call (the graph
    diet's boundary discipline)."""
    q_arr = q_pts
    p_arr = p_pts
    bits = jnp.asarray(_ATE_BITS, jnp.bool_)
    f0 = T.fv12_out(T.fv12_one(q_pts.shape[:-3]))
    r0 = jnp.asarray(q_arr, jnp.int32)

    def body(i, carry):
        r_arr, f_arr = carry
        R = _wrap_g2(r_arr)
        Pp = _wrap_g1(p_arr)
        f = T.fv12_in(f_arr, RED_BOUND)
        R2, line = _dbl_step(R, Pp)
        f2 = _mul_line(T.fv12_square(f), line)
        R3, line_a = _add_step(R2, _wrap_g2(q_arr), Pp)
        f3 = _mul_line(f2, line_a)
        # ONE stacked reduce for every carry component of the body:
        # both branch points (12 Fp comps) + both f values (24)
        comps = ([c for pt in (R2, R3) for fc in pt
                  for c in (fc.c0, fc.c1)]
                 + T.fv12_comps(f2) + T.fv12_comps(f3))
        red = BF.fv_reduce_stack(comps)
        bit = bits[i]
        r_out = jnp.where(bit, T.stack_fv2_comps(red, 6, n=3),
                          T.stack_fv2_comps(red, 0, n=3))
        f_out = jnp.where(bit, T.stack_fv2_comps(red, 24),
                          T.stack_fv2_comps(red, 12))
        return r_out, f_out

    _, f_arr = jax.lax.fori_loop(0, len(_ATE_BITS), body, (r0, f0))
    return T.fv12_in(f_arr, RED_BOUND)


# --- final exponentiation ----------------------------------------------------

def _pow_static(f: FV12, e: int) -> FV12:
    """f^e for UNITARY f and a static POSITIVE exponent: rolled
    cyclotomic square-and-multiply over e's bits (one csq body + one
    mul body + one stacked carry reduce per instantiation — the hard
    part uses exactly THREE instantiations, over (x-1)^2, |x| and
    x^2, instead of five chained |x| loops)."""
    assert e > 0
    bit_list = tuple((e >> i) & 1
                     for i in range(e.bit_length() - 2, -1, -1))
    bits = jnp.asarray(bit_list, jnp.bool_)
    base = T.fv12_out(_red12(f))

    def body(i, acc):
        a = T.fv12_in(acc, RED_BOUND)
        sq = T.fv12_cyclotomic_square(a)
        mul = T.fv12_mul(sq, T.fv12_in(base, RED_BOUND))
        red = BF.fv_reduce_stack(T.fv12_comps(sq)
                                 + T.fv12_comps(mul))
        return jnp.where(bits[i], T.stack_fv2_comps(red, 12),
                         T.stack_fv2_comps(red, 0))

    out = jax.lax.fori_loop(0, len(bit_list), body, base)
    return T.fv12_in(out, RED_BOUND)


def final_exponentiate(x: FV12) -> FV12:
    """x^(3 (p^12-1)/r) — the CUBE of `bls_ref.final_exponentiate`
    (module docstring; verdict-equivalent, differential-pinned).
    Easy part (p^6-1)(p^2+1) pays the one Fp12 inversion; hard part
    3H via the x-chain: a = m^((x-1)^2), b = a^(x+p) =
    conj(a^|x|) frob(a), c = b^(x^2+p^2-1) = b^(x^2) frob^2(b)
    conj(b), result = c * m^3 — unitary inverses are conjugations,
    and every exponent is a static positive integer."""
    m = T.fv12_mul(T.fv12_conj(x), T.fv12_inv(x))          # ^(p^6-1)
    m = T.fv12_mul(T.fv12_frob(T.fv12_frob(m)), m)         # ^(p^2+1)
    a = _pow_static(m, (_X - 1) ** 2)                      # ^(x-1)^2
    b = T.fv12_mul(T.fv12_conj(_pow_static(a, -_X)),       # ^x (x<0)
                   T.fv12_frob(a))                         # * ^p
    c = T.fv12_mul(
        T.fv12_mul(_pow_static(b, _X * _X),                # ^(x^2)
                   T.fv12_frob(T.fv12_frob(b))),           # ^(p^2)
        T.fv12_conj(b))                                    # ^(-1)
    return T.fv12_mul(c, T.fv12_mul(T.fv12_square(m), m))  # * m^3


# --- identity detection + the registered entry -------------------------------

def _z_is_zero_g1(p_pts: jnp.ndarray) -> jnp.ndarray:
    """[..., 3, NLIMBS] -> [...] bool: Z == 0 (mod p)."""
    return BF.fv_eq_mod_p(FV(p_pts[..., 2, :], RED_BOUND), 0)


def _z_is_zero_g2(q_pts: jnp.ndarray) -> jnp.ndarray:
    z = q_pts[..., 2, :, :]                       # [..., 2, NLIMBS]
    strict = BF.reduce_cols(z, BF._ELEM_LIMB + BF.LMASK)
    return (BF.strict_eq_mod_p(strict[..., 0, :], 0)
            & BF.strict_eq_mod_p(strict[..., 1, :], 0))


def bls_pairing_product(p_pts: jnp.ndarray,
                        q_pts: jnp.ndarray,
                        pallas_field=False) -> jnp.ndarray:
    """ALL closed classes' pairing checks in one dispatch.

    p_pts [C, 2, 3, NLIMBS]    — per (class, pair) projective G1
    q_pts [C, 2, 3, 2, NLIMBS] — per (class, pair) projective G2

    Pair layout (the lane's packing): pair 0 = (-G1, asig), pair 1 =
    (apk, H(class message)).  Returns ok [C] bool:
    prod_k e(p_k, q_k) == 1, with a pair whose EITHER point is the
    identity skipped (`bls_ref.pairing_product_is_one` semantics —
    an all-identity padding class returns True and is ignored by the
    caller).  Shapes (+ the STATIC `pallas_field` kernel-lane knob,
    see `bls_jax.bls_aggregate`) are the compile key; the lane pads
    the class count onto `ShapeLadder.bls_class_rungs`, so the jit
    cache holds one executable per class rung."""
    with BF.field_backend(pallas_field):
        f = miller_loop(q_pts, p_pts)             # batch [C, 2]
        skip = _z_is_zero_g1(p_pts) | _z_is_zero_g2(q_pts)  # [C, 2]
        f_arr = T.fv12_out(_red12(f))
        one = T.fv12_out(T.fv12_one(f_arr.shape[:-3]))
        f_arr = jnp.where(skip[..., None, None, None], one, f_arr)
        f0 = T.fv12_in(f_arr[..., 0, :, :, :], RED_BOUND)
        f1 = T.fv12_in(f_arr[..., 1, :, :, :], RED_BOUND)
        out = final_exponentiate(T.fv12_mul(f0, f1))
        return T.fv12_eq_one(out)


bls_pairing_product_jit = jax.jit(bls_pairing_product,
                                  static_argnames=("pallas_field",))

from agnes_tpu.device import registry as _registry  # noqa: E402

_registry.register(_registry.EntrySpec(
    name="bls_pairing_product", fn=bls_pairing_product,
    jit=bls_pairing_product_jit, statics=("pallas_field",), hot=True,
    pallas_backends=("tpu", "interpret")))

# kernel-lane census alias (see bls_jax.bls_aggregate_pallas)
_registry.register(_registry.EntrySpec(
    name="bls_pairing_product_pallas", fn=bls_pairing_product,
    jit=bls_pairing_product_jit, statics=("pallas_field",), hot=False,
    pallas_backends=("tpu", "interpret")))


# --- host-side packing -------------------------------------------------------

def pack_g1_proj(pt) -> np.ndarray:
    """bls_ref affine G1 point (or None) -> [3, NLIMBS] projective."""
    out = np.zeros((3, NLIMBS), np.int32)
    if pt is None:
        out[1] = BF.to_limbs(1)
        return out
    out[0] = BF.to_limbs(pt[0])
    out[1] = BF.to_limbs(pt[1])
    out[2] = BF.to_limbs(1)
    return out


def pack_g2_proj(pt) -> np.ndarray:
    """bls_ref affine G2 point (or None) -> [3, 2, NLIMBS]."""
    out = np.zeros((3, 2, NLIMBS), np.int32)
    if pt is None:
        out[1, 0] = BF.to_limbs(1)
        return out
    x, y = pt
    out[0, 0] = BF.to_limbs(x.c[0])
    out[0, 1] = BF.to_limbs(x.c[1])
    out[1, 0] = BF.to_limbs(y.c[0])
    out[1, 1] = BF.to_limbs(y.c[1])
    out[2, 0] = BF.to_limbs(1)
    return out


#: the constant first-pair G1 point of every class: -G1
NEG_G1_LIMBS: np.ndarray = pack_g1_proj(ref.point_neg(ref.G1))
