"""NativeIngestLoop: the C++ ingestion event loop (ctypes wrapper).

The native twin of `bridge.VoteBatcher` — SURVEY.md §2.7's "C++ event
loop feeding device batches; double-buffered host<->device queues"
slot, re-imagining the reference's one-vote-at-a-time executor loop
(reference consensus_executor.rs:24-49) as a batch pipeline in
core/native/ingest.cpp.  Wire votes arrive as PACKED BYTES (the
network-facing ABI; `pack_wire_votes` builds them from columns), flow
through parse -> screen -> window discipline -> TPU batch verify ->
dedup/layer/intern -> dense [I, V] phases, with rotated-out rounds
falling back to the exact C++ RoundVotes host tally (late
precommit-value quorums surface via `drain_host_events`, because
commit-from-any-round — reference state_machine.rs:211 — must fire no
matter how late the quorum assembles).

Differential parity with VoteBatcher: tests/test_native_ingest.py.

Double buffering: `ag_ing_emit` flips between two phase-buffer sets,
so the numpy views a previous emit handed to the device remain stable
while C++ fills the other set — the host<->device queue overlap the
SURVEY names.  Views are zero-copy; jnp.asarray at the device boundary
makes the device copy.
"""

from __future__ import annotations

import ctypes
from typing import List, Optional, Tuple

import numpy as np

from agnes_tpu.core.native_build import lib as _build_lib

# jax + device.step are imported INSIDE build_phases (the only device-
# boundary method): the wire codec (pack/unpack) and the loop's host
# half must stay importable jax-free — the serve admission path and
# the pre-test model-checker gate (analysis/admission_mc.py) depend
# on it.

REC_SIZE = 96

_configured = False


def _lib() -> ctypes.CDLL:
    global _configured
    L = _build_lib()
    if not _configured:
        c = ctypes
        L.ag_ing_new.restype = c.c_void_p
        L.ag_ing_new.argtypes = [c.c_int64, c.c_int64, c.c_int64,
                                 c.c_int64, c.c_char_p, c.c_void_p]
        L.ag_ing_set_held_cap.argtypes = [c.c_void_p, c.c_int64]
        L.ag_ing_free.argtypes = [c.c_void_p]
        L.ag_ing_sync.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p]
        L.ag_ing_push.restype = c.c_int64
        L.ag_ing_push.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]
        L.ag_ing_stage.restype = c.c_int64
        L.ag_ing_stage.argtypes = [c.c_void_p]
        L.ag_ing_fill_verify_inputs.argtypes = [c.c_void_p, c.c_void_p,
                                                c.c_void_p, c.c_void_p]
        L.ag_ing_apply_verdicts.restype = c.c_int64
        L.ag_ing_apply_verdicts.argtypes = [c.c_void_p, c.c_char_p]
        L.ag_ing_emit.restype = c.c_int64
        L.ag_ing_emit.argtypes = [c.c_void_p]
        L.ag_ing_phase.restype = c.c_int64
        L.ag_ing_phase.argtypes = [
            c.c_void_p, c.c_int64, c.POINTER(c.c_int32),
            c.POINTER(c.c_int32), c.POINTER(c.c_int64),
            c.POINTER(c.POINTER(c.c_int32)),
            c.POINTER(c.POINTER(c.c_uint8))]
        L.ag_ing_drain_events.restype = c.c_int64
        L.ag_ing_drain_events.argtypes = [c.c_void_p, c.c_void_p,
                                          c.c_int64]
        L.ag_ing_decode_slot.restype = c.c_int64
        L.ag_ing_decode_slot.argtypes = [c.c_void_p, c.c_int64, c.c_int32]
        L.ag_ing_evidence.restype = c.c_int64
        L.ag_ing_evidence.argtypes = [c.c_void_p, c.c_int64, c.c_int64,
                                      c.c_char_p]
        L.ag_ing_clear_log.argtypes = [c.c_void_p]
        L.ag_ing_counters.argtypes = [c.c_void_p, c.c_void_p]
        L.ag_ing_export_slots.argtypes = [c.c_void_p, c.c_void_p]
        L.ag_ing_import_slots.argtypes = [c.c_void_p, c.c_void_p]
        L.ag_ing_log_size.restype = c.c_int64
        L.ag_ing_log_size.argtypes = [c.c_void_p]
        L.ag_ing_export_log.argtypes = [c.c_void_p, c.c_void_p]
        L.ag_ing_import_log.restype = c.c_int64
        L.ag_ing_import_log.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]
        L.ag_ing_restore_counters.argtypes = [c.c_void_p, c.c_void_p]
        L.ag_ing_get_held_cap.restype = c.c_int64
        L.ag_ing_get_held_cap.argtypes = [c.c_void_p]
        L.ag_ing_push_async.restype = c.c_int64
        L.ag_ing_push_async.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]
        L.ag_ing_flush.argtypes = [c.c_void_p]
        L.ag_ing_async_depth.restype = c.c_int64
        L.ag_ing_async_depth.argtypes = [c.c_void_p]
        L.ag_ing_set_validators.restype = c.c_int64
        L.ag_ing_set_validators.argtypes = [c.c_void_p, c.c_char_p,
                                            c.c_void_p]
        _configured = True
    return L


def pack_wire_votes(instance, validator, height, round_, typ, value,
                    signatures: Optional[np.ndarray] = None) -> bytes:
    """Column arrays -> packed 96-byte wire records (vectorized).
    value < 0 encodes nil."""
    n = len(np.asarray(instance))
    rec = np.zeros((n, REC_SIZE), np.uint8)
    rec[:, 0:4] = np.asarray(instance, np.uint32)[:, None].view(
        np.uint8).reshape(n, 4)
    rec[:, 4:8] = np.asarray(validator, np.uint32)[:, None].view(
        np.uint8).reshape(n, 4)
    rec[:, 8:16] = np.asarray(height, np.int64)[:, None].view(
        np.uint8).reshape(n, 8)
    rec[:, 16:20] = np.asarray(round_, np.int32)[:, None].view(
        np.uint8).reshape(n, 4)
    rec[:, 20] = np.asarray(typ, np.uint8)
    val = np.asarray(value, np.int64)
    rec[:, 21] = (val >= 0).astype(np.uint8)
    rec[:, 24:32] = np.maximum(val, 0)[:, None].view(
        np.uint8).reshape(n, 8)
    if signatures is not None:
        rec[:, 32:96] = np.asarray(signatures, np.uint8).reshape(n, 64)
    return rec.tobytes()


def unpack_wire_votes(wire) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                     np.ndarray, np.ndarray, np.ndarray,
                                     np.ndarray]:
    """Packed 96-byte wire records -> column arrays (vectorized): the
    exact inverse of `pack_wire_votes`, for HOST consumers of the wire
    ABI (the serve plane's admission queue screens and fairness-
    accounts records before they reach a batcher).  Returns (instance,
    validator, height, round, typ, value, signatures[N, 64]); value is
    -1 for nil.  A trailing partial record is DROPPED (the caller
    counts it via `len(wire) % REC_SIZE`)."""
    buf = np.frombuffer(wire, np.uint8) if isinstance(wire, (bytes,
                                                             bytearray,
                                                             memoryview)) \
        else np.asarray(wire, np.uint8).ravel()
    n = len(buf) // REC_SIZE
    rec = buf[:n * REC_SIZE].reshape(n, REC_SIZE)

    def field(lo, hi, dt):
        return np.ascontiguousarray(rec[:, lo:hi]).view(dt)[:, 0]

    inst = field(0, 4, np.uint32).astype(np.int64)
    val = field(4, 8, np.uint32).astype(np.int64)
    height = field(8, 16, np.int64).copy()
    round_ = field(16, 20, np.int32).astype(np.int64)
    typ = rec[:, 20].astype(np.int64)
    nonnil = rec[:, 21] != 0
    value = np.where(nonnil, field(24, 32, np.int64), -1)
    sigs = np.ascontiguousarray(rec[:, 32:96])
    return inst, val, height, round_, typ, value, sigs


class NativeIngestLoop:
    """One C++ ingestion loop per (driver, height window) — the native
    fast lane with the same tick protocol as VoteBatcher."""

    def __init__(self, n_instances: int, n_validators: int,
                 n_slots: int, n_rounds: int = 4,
                 pubkeys: Optional[np.ndarray] = None,
                 powers: Optional[np.ndarray] = None,
                 held_cap: Optional[int] = None):
        self.I, self.V = n_instances, n_validators
        self._n_slots, self._n_rounds = n_slots, n_rounds
        self.signed = pubkeys is not None
        L = _lib()
        if pubkeys is not None:
            pubkeys = np.ascontiguousarray(pubkeys, np.uint8)
            if pubkeys.shape != (n_validators, 32):
                # the C side copies V*32 bytes blind; screen here
                # (the wrapper-screen contract of core/native.py)
                raise ValueError(
                    f"pubkeys must be [{n_validators}, 32] uint8, "
                    f"got {pubkeys.shape}")
        pk = pubkeys.tobytes() if pubkeys is not None else None
        pw = None
        if powers is not None:
            pw = np.ascontiguousarray(powers, np.int64)
            if pw.shape != (n_validators,):
                raise ValueError(
                    f"powers must be [{n_validators}], got {pw.shape}")
        self._powers = pw
        self._h = L.ag_ing_new(
            n_instances, n_validators, n_rounds, n_slots, pk,
            pw.ctypes.data if pw is not None else None)
        if not self._h:
            # the C side fails closed (NULL) on hostile dimensions
            raise ValueError(
                f"invalid ingest-loop dimensions: I={n_instances} "
                f"V={n_validators} W={n_rounds} S={n_slots}")
        self._free = L.ag_ing_free
        if held_cap is not None:
            # raw ABI treats cap <= 0 as reset-to-default; the wrapper
            # contract (shared with VoteBatcher) requires a positive cap
            if int(held_cap) <= 0:
                raise ValueError(f"held_cap must be positive: {held_cap}")
            L.ag_ing_set_held_cap(self._h, int(held_cap))
        # read back the enforced cap — the C side owns the default
        self.held_cap = int(L.ag_ing_get_held_cap(self._h))
        # freshness for import_state: ANY interaction (push/sync/build/
        # clear_log) makes the loop non-restorable — the evidence log
        # alone is a weak proxy (pushed-but-unbuilt votes leave it empty)
        self._used = False

    def __del__(self):
        if getattr(self, "_h", None):
            self._free(self._h)
            self._h = None

    # -- tick protocol -------------------------------------------------------

    def sync_device(self, base_round, heights) -> None:
        base = np.ascontiguousarray(base_round, np.int64)
        hts = np.ascontiguousarray(heights, np.int64)
        if base.shape != (self.I,) or hts.shape != (self.I,):
            # the C side reads I int64s from each blind (OOB otherwise)
            raise ValueError(
                f"base_round/heights must be [{self.I}], got "
                f"{base.shape}/{hts.shape}")
        self._heights = hts
        self._base_round = base
        self._used = True
        _lib().ag_ing_sync(self._h, base.ctypes.data, hts.ctypes.data)

    def push(self, wire_bytes: bytes) -> int:
        """Packed wire records in; returns lanes accepted (held counts
        as accepted; rejects show up in `counters`)."""
        n = len(wire_bytes) // REC_SIZE
        self._used = True
        return _lib().ag_ing_push(self._h, wire_bytes, n)

    def push_async(self, wire_bytes: bytes) -> int:
        """Queue packed wire records for the C++ worker thread, which
        parses + malformed-screens them CONCURRENTLY with whatever the
        caller does next (drive the device step, pack the next batch) —
        the host-driver overlap of SURVEY.md §2.7.  Returns the record
        count queued; `build_phases` (and `flush`) synchronize, so
        per-tick semantics are identical to `push` — differential:
        tests/test_native_ingest.py async suite."""
        n = len(wire_bytes) // REC_SIZE
        self._used = True
        return _lib().ag_ing_push_async(self._h, wire_bytes, n)

    def flush(self) -> None:
        """Block until every queued async buffer has been parsed into
        the pending set (build_phases implies this via stage)."""
        _lib().ag_ing_flush(self._h)

    @property
    def async_depth(self) -> int:
        """Records queued or mid-parse on the worker thread."""
        return int(_lib().ag_ing_async_depth(self._h))

    def set_validators(self, pubkeys: Optional[np.ndarray] = None,
                       powers: Optional[np.ndarray] = None) -> None:
        """Validator-set epoch (reference validators.rs:38-46 intent,
        SURVEY §2.6 "re-uploaded on set changes"): swap the pubkey
        table (key rotation) and/or voting powers AT A HEIGHT BOUNDARY
        — call right after the sync_device that advanced heights.  A
        power of 0 models removal; None leaves a table unchanged."""
        self.flush()                     # no worker batch mid-parse
        pk = None
        if pubkeys is not None:
            if not self.signed:
                raise ValueError(
                    "pubkey upload on an unsigned loop (verification "
                    "policy is construction-time)")
            pubkeys = np.ascontiguousarray(pubkeys, np.uint8)
            if pubkeys.shape != (self.V, 32):
                raise ValueError(
                    f"pubkeys must be [{self.V}, 32], got {pubkeys.shape}")
            pk = pubkeys.tobytes()
        pw = None
        if powers is not None:
            pw = np.ascontiguousarray(powers, np.int64)
            if pw.shape != (self.V,):
                raise ValueError(
                    f"powers must be [{self.V}], got {pw.shape}")
            self._powers = pw
        self._used = True
        rc = _lib().ag_ing_set_validators(
            self._h, pk, pw.ctypes.data if pw is not None else None)
        if rc < 0:
            raise ValueError("set_validators rejected by the native loop")

    def build_phases(self) -> List[Tuple[VotePhase, int]]:
        """Stage -> (verify on device if signed) -> emit.  Returns
        [(phase, n_votes)] like VoteBatcher.build_phases; the phase
        arrays are zero-copy views into the C++ double buffer."""
        import jax.numpy as jnp

        from agnes_tpu.device.step import VotePhase

        L = _lib()
        self._used = True
        n = L.ag_ing_stage(self._h)
        if n == 0:
            ok = None
        elif self.signed:
            from agnes_tpu.crypto import ed25519_jax as ejax

            pub = np.empty((n, 32), np.int32)
            sig = np.empty((n, 64), np.int32)
            blocks = np.empty((n, 32), np.uint32)
            L.ag_ing_fill_verify_inputs(
                self._h, pub.ctypes.data, sig.ctypes.data,
                blocks.ctypes.data)
            good = np.asarray(ejax.verify_batch_jit(
                jnp.asarray(pub), jnp.asarray(sig),
                jnp.asarray(blocks.reshape(n, 1, 32))))
            ok = np.ascontiguousarray(good, np.uint8)
        else:
            ok = None
        if n:
            rc = L.ag_ing_apply_verdicts(
                self._h, ok.tobytes() if ok is not None else None)
            if rc < 0:      # not an assert: must survive python -O
                raise RuntimeError(
                    "ag_ing_apply_verdicts rejected the tick (signed "
                    "loop requires verdicts)")
        n_phases = L.ag_ing_emit(self._h)
        hts = jnp.asarray(getattr(
            self, "_heights", np.zeros(self.I, np.int64)).astype(np.int32))
        out: List[Tuple[VotePhase, int]] = []
        c = ctypes
        for k in range(n_phases):
            rnd, typ = c.c_int32(), c.c_int32()
            nv = c.c_int64()
            slots_p = c.POINTER(c.c_int32)()
            mask_p = c.POINTER(c.c_uint8)()
            L.ag_ing_phase(self._h, k, c.byref(rnd), c.byref(typ),
                           c.byref(nv), c.byref(slots_p), c.byref(mask_p))
            slots = np.ctypeslib.as_array(
                slots_p, shape=(self.I, self.V))
            mask = np.ctypeslib.as_array(
                mask_p, shape=(self.I, self.V))
            out.append((VotePhase(
                round=jnp.full(self.I, int(rnd.value), jnp.int32),
                typ=jnp.full(self.I, int(typ.value), jnp.int32),
                slots=jnp.asarray(slots),
                mask=jnp.asarray(mask.astype(bool)),
                height=hts), int(nv.value)))
        return out

    # -- host fallback / evidence / introspection ----------------------------

    def drain_host_events(self) -> List[Tuple[int, int, int, int]]:
        buf = np.empty((64, 4), np.int64)
        out: List[Tuple[int, int, int, int]] = []
        while True:
            n = _lib().ag_ing_drain_events(self._h, buf.ctypes.data, 64)
            out.extend(tuple(int(x) for x in row) for row in buf[:n])
            if n < 64:
                return out

    def decode_slot(self, instance: int, slot: int) -> Optional[int]:
        v = _lib().ag_ing_decode_slot(self._h, instance, slot)
        return None if v < 0 else int(v)

    def signed_evidence(self, instance: int, validator: int
                        ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Two conflicting signed wire records ([96] uint8 each), or
        None — the slashable proof for a device equivocation flag."""
        buf = ctypes.create_string_buffer(2 * REC_SIZE)
        if not _lib().ag_ing_evidence(self._h, instance, validator, buf):
            return None
        raw = np.frombuffer(buf.raw, np.uint8)
        return raw[:REC_SIZE].copy(), raw[REC_SIZE:].copy()

    def clear_log(self) -> None:
        self._used = True
        _lib().ag_ing_clear_log(self._h)

    # -- snapshot surface (utils.checkpoint.save/load_native_loop) ----------

    def export_state(self) -> dict:
        """The durable state a crash must not lose: slot maps (decision
        decode), the verified-vote log (slashing evidence), counters,
        and the synced window.  In-flight votes are not exported (a
        restarted node re-receives them from peers)."""
        L = _lib()
        slots = np.empty(self.I * self._n_slots, np.int64)
        L.ag_ing_export_slots(self._h, slots.ctypes.data)
        n = L.ag_ing_log_size(self._h)
        log = np.empty((n, REC_SIZE), np.uint8)
        if n:
            L.ag_ing_export_log(self._h, log.ctypes.data)
        c = self.counters
        return {
            "slots": slots.reshape(self.I, self._n_slots),
            "log": log,
            "counters": np.asarray(
                [c["rejected_malformed"], c["dropped_stale_height"],
                 c["rejected_signature"], c["overflow_votes"],
                 c["dropped_held_overflow"]], np.int64),
            "heights": getattr(self, "_heights",
                               np.zeros(self.I, np.int64)),
            "base_round": getattr(self, "_base_round",
                                  np.zeros(self.I, np.int64)),
        }

    def import_state(self, st: dict) -> None:
        L = _lib()
        # snapshots restore only into a FRESH loop: merging into live
        # state would mix pre-restore votes/evidence with the
        # snapshot's slots/window/counters.  `_used` trips on ANY
        # interaction (push/sync/build/clear_log) — the log-emptiness
        # check alone would miss pushed-but-unbuilt votes; the C-side
        # log guard (ingest.cpp ag_ing_import_log) stays as defense in
        # depth for direct ABI users.
        if self._used:
            raise RuntimeError(
                "import_state: loop has already been used (push/sync/"
                "build); snapshots restore only into a fresh loop")
        # validate EVERY leaf before mutating anything: a malformed
        # snapshot must not leave a half-imported loop behind
        slots = np.ascontiguousarray(st["slots"], np.int64)
        if slots.shape != (self.I, self._n_slots):
            raise ValueError(f"slots must be [{self.I}, {self._n_slots}]")
        log = np.ascontiguousarray(st["log"], np.uint8)
        if log.ndim != 2 or log.shape[1] != REC_SIZE:
            # the C side reads n*96 bytes blind; screen the shape here
            raise ValueError(f"log must be [n, {REC_SIZE}]: {log.shape}")
        cnt = np.ascontiguousarray(st["counters"], np.int64)
        if cnt.shape != (5,):
            raise ValueError("counters must be [5]")
        base = np.ascontiguousarray(st["base_round"], np.int64)
        hts = np.ascontiguousarray(st["heights"], np.int64)
        if base.shape != (self.I,) or hts.shape != (self.I,):
            # load-bearing duplicate of sync_device's screen: sync runs
            # AFTER the log import below, so its own check would fire
            # too late to keep a failed import side-effect-free
            raise ValueError(f"base_round/heights must be [{self.I}]")

        if len(log):
            # the C side screens record CONTENT two-pass (a corrupt
            # snapshot commits nothing); run it first so a failure
            # leaves the loop fully untouched
            dropped = L.ag_ing_import_log(self._h, log.tobytes(),
                                          len(log))
            if dropped:
                # >0: records failed the malformed screen; -1: C-side
                # fresh-only refusal (unreachable via this method — the
                # _used guard above fires first; the -1 exists for
                # direct ABI users of ag_ing_import_log)
                raise RuntimeError(
                    f"snapshot log rejected (code {dropped}): corrupt "
                    "records or non-fresh loop; nothing was imported")
        self.sync_device(base, hts)
        L.ag_ing_import_slots(self._h, slots.ctypes.data)
        L.ag_ing_restore_counters(self._h, cnt.ctypes.data)

    @property
    def counters(self) -> dict:
        buf = np.empty(7, np.int64)
        _lib().ag_ing_counters(self._h, buf.ctypes.data)
        return {"rejected_malformed": int(buf[0]),
                "dropped_stale_height": int(buf[1]),
                "rejected_signature": int(buf[2]),
                "overflow_votes": int(buf[3]),
                "held": int(buf[4]),
                "log": int(buf[5]),
                "dropped_held_overflow": int(buf[6])}
