"""Host <-> device bridge: the vote-batch ingestion ABI.

The reference's L4/L6 boundary ("the consumer is responsible for
networking... and deciding when received messages constitute an Event",
README.md:46-49) is exactly where the TPU/host boundary goes (SURVEY.md
§1).  This package is that boundary's host side:

  value_table.py  payload <-> 31-bit value id interning (types.py:
                  values on device are fixed-width ids; arbitrary
                  payloads live here), plus the per-instance dense
                  slot mapping the tally kernels index by.
  ingest.py       VoteBatcher: sparse signed wire votes in, batched
                  signature verification + dense per-(round, class)
                  VotePhase matrices out (vectorized numpy).
  native_ingest.py  NativeIngestLoop: the C++ event loop twin of
                  VoteBatcher (core/native/ingest.cpp) — packed wire
                  BYTES in, double-buffered dense phases out; the
                  network-facing fast lane.
  evidence.py     the slashing join: device equivocation flags +
                  either bridge's retained verified votes ->
                  third-party-verifiable signed double-sign proofs.

The device side of the ABI is device/step.py's VotePhase/ExtEvent and
the validator table from ValidatorSet.device_arrays().
"""

from agnes_tpu.bridge.native_ingest import (  # noqa: F401
    NativeIngestLoop,
    pack_wire_votes,
)
from agnes_tpu.bridge.value_table import SlotMap, ValueTable  # noqa: F401

# ingest (VoteBatcher densify -> device VotePhase) and evidence (the
# slashing join over device flags) import jax at module top; the wire
# codec / native loop / value table above are pure numpy+ctypes.
# Resolving the jax-bearing members lazily keeps the admission path
# and the pre-test model-checker gate jax-free (serve/__init__.py has
# the same split).
from agnes_tpu.utils.lazy import make_lazy_getattr  # noqa: E402

__getattr__ = make_lazy_getattr(__name__, {
    "DeviceEvidence": ("agnes_tpu.bridge.evidence", "DeviceEvidence"),
    "collect_device_evidence": ("agnes_tpu.bridge.evidence",
                                "collect_device_evidence"),
    "verify_evidence": ("agnes_tpu.bridge.evidence", "verify_evidence"),
    "VoteBatcher": ("agnes_tpu.bridge.ingest", "VoteBatcher"),
    "WireVote": ("agnes_tpu.bridge.ingest", "WireVote"),
}, globals())
