"""Value interning and per-instance slot mapping.

Device values are 31-bit ids (types.py design decision: the
reference's `Value {}` placeholder becomes a fixed-width lane);
payloads stay on host.  The tally kernels index value buckets by an
instance-local dense *slot* in [0, n_slots) — the bridge owns both
mappings (device/tally.py "the bridge owns the slot<->value-id
mapping").
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional

MAX_VALUE_ID = 2**31 - 1


class ValueTable:
    """payload bytes <-> value id.  Ids are content-derived (31-bit
    truncated SHA-512/256 of the payload) so independent hosts agree on
    ids without coordination; collisions fall back to probing, which
    stays host-local consistent for the payloads this host saw."""

    def __init__(self):
        self._by_id: Dict[int, bytes] = {}
        self._by_payload: Dict[bytes, int] = {}

    def intern(self, payload: bytes) -> int:
        vid = self._by_payload.get(payload)
        if vid is not None:
            return vid
        digest = hashlib.sha512(payload).digest()
        vid = int.from_bytes(digest[:4], "little") & MAX_VALUE_ID
        while vid in self._by_id and self._by_id[vid] != payload:
            vid = (vid + 1) & MAX_VALUE_ID       # linear probe
        self._by_id[vid] = payload
        self._by_payload[payload] = vid
        return vid

    def payload(self, vid: int) -> Optional[bytes]:
        return self._by_id.get(vid)

    def __len__(self) -> int:
        return len(self._by_id)


class SlotMap:
    """Per-instance dense slot allocation for value ids.

    `n_slots` is the tally's static S; at most S distinct non-nil
    values can be tracked per instance window.  Overflowing values get
    slot None — the caller routes those votes to the host tally
    (the documented host-fallback path for adversarial many-value
    floods, SURVEY.md §7 hard part 2)."""

    def __init__(self, n_instances: int, n_slots: int):
        self.n_slots = n_slots
        self._maps: List[Dict[int, int]] = [dict()
                                            for _ in range(n_instances)]
        self.overflowed: int = 0
        # dense [I, S] slot -> value-id export (-1 = unallocated),
        # maintained incrementally so the native densify drain
        # (ISSUE 20) can scan it by POINTER — value ids are 31-bit
        # non-negative, so -1 is a safe sentinel.  numpy is imported
        # lazily to keep this module's import surface unchanged for
        # the table-only users.
        import numpy as _np
        self.dense = _np.full((n_instances, n_slots), -1, _np.int64)

    def slot_for(self, instance: int, value_id: int) -> Optional[int]:
        m = self._maps[instance]
        slot = m.get(value_id)
        if slot is not None:
            return slot
        if len(m) >= self.n_slots:
            self.overflowed += 1
            return None
        slot = len(m)
        m[value_id] = slot
        self.dense[instance, slot] = value_id
        return slot

    def prealloc(self, instance: int, value_id: int) -> None:
        """Allocate a slot if there is room; unlike slot_for, a full
        map is NOT counted as an overflow attempt.  Used by batch
        pre-passes that fix allocation ORDER (combined ascending across
        vote classes) before the per-class interning that does the real
        per-vote accounting."""
        m = self._maps[instance]
        if value_id not in m and len(m) < self.n_slots:
            self.dense[instance, len(m)] = value_id
            m[value_id] = len(m)

    def value_for(self, instance: int, slot: int) -> Optional[int]:
        for vid, s in self._maps[instance].items():
            if s == slot:
                return vid
        return None

    def reset_instance(self, instance: int) -> None:
        """Free an instance's slots (height advance)."""
        self._maps[instance].clear()
        self.dense[instance, :] = -1
