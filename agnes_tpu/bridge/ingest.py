"""VoteBatcher: sparse signed wire votes -> dense device phases.

The ingestion path of the north star: wire votes carrying (instance,
validator, round, class, value, signature) are batch-verified (JAX
Ed25519 data plane; C++ fallback) and densified into the [I, V]
VotePhase matrices the fused step consumes.  Votes that share an
(instance, validator, round, class) cell cannot ride one dense matrix,
so the batcher *layers* them: layer k holds each cell's k-th vote —
conflicting (equivocating) votes land in later layers and still reach
the device, where the tally's seen-record flags the double-sign.

The reference's analogue is the one-vote-at-a-time
`VoteExecutor::apply` loop (vote_executor.rs:20-23, SURVEY §3.2); this
is that loop turned into a batched device pipeline.
"""

from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from agnes_tpu.bridge.value_table import SlotMap
from agnes_tpu.crypto.encoding import vote_signing_bytes
from agnes_tpu.device.step import VotePhase
from agnes_tpu.device.tally import VOTED_NIL
from agnes_tpu.types import NIL_ID, VoteType


@dataclass(frozen=True)
class WireVote:
    """One signed vote addressed to a consensus instance."""

    instance: int
    validator: int
    height: int
    round: int
    typ: VoteType
    value: Optional[int]       # None = nil
    signature: Optional[bytes] = None


class VoteBatcher:
    """Collects wire votes for one ingestion tick and emits dense
    phases.  One batcher per (driver, height window)."""

    def __init__(self, n_instances: int, n_validators: int, n_slots: int,
                 heights: Optional[np.ndarray] = None):
        self.I, self.V = n_instances, n_validators
        self.slots = SlotMap(n_instances, n_slots)
        # per-instance height (defaults: all at height 0)
        self.heights = (heights if heights is not None
                        else np.zeros(n_instances, np.int64))
        self._pending: List[WireVote] = []
        self.rejected_signature = 0
        self.rejected_malformed = 0
        self.overflow_votes: List[WireVote] = []

    def add(self, vote: WireVote) -> None:
        self._pending.append(vote)

    def extend(self, votes) -> None:
        self._pending.extend(votes)

    # -- signature verification ---------------------------------------------

    def _verify_batch(self, votes: List[WireVote],
                      pubkeys: np.ndarray) -> List[bool]:
        """Batch-verify on the JAX plane; pubkeys [V, 32] uint8 is the
        device-resident validator table (ValidatorSet.device_arrays)."""
        from agnes_tpu.crypto import ed25519_jax as ejax

        pks, msgs, sigs = [], [], []
        for v in votes:
            pks.append(pubkeys[v.validator].tobytes())
            msgs.append(vote_signing_bytes(v.height, v.round, int(v.typ),
                                           v.value))
            sigs.append(v.signature or b"\x00" * 64)
        pub, sig, blocks = ejax.pack_verify_inputs_host(pks, msgs, sigs)
        ok = ejax.verify_batch_jit(pub, sig, blocks)
        return np.asarray(ok).tolist()

    # -- densification -------------------------------------------------------

    def build_phases(self, pubkeys: Optional[np.ndarray] = None
                     ) -> List[Tuple[VotePhase, int]]:
        """Drain pending votes into dense phases.

        Returns [(phase, n_votes)], one per (round, class, layer),
        deterministic order.  With `pubkeys` given, signatures are
        batch-verified first and failures dropped (and counted)."""
        votes, self._pending = self._pending, []
        keep = []
        for v in votes:
            if not (0 <= v.instance < self.I and 0 <= v.validator < self.V
                    and v.round >= 0
                    and (v.value is None or 0 <= v.value < 2**31)
                    and (v.signature is None or len(v.signature) == 64)
                    and v.height == self.heights[v.instance]):
                self.rejected_malformed += 1
                continue
            keep.append(v)
        if pubkeys is not None and keep:
            ok = self._verify_batch(keep, pubkeys)
            self.rejected_signature += len(keep) - sum(ok)
            keep = [v for v, good in zip(keep, ok) if good]

        # exact-duplicate dedup: gossip redelivery of the same vote must
        # not burn a whole dense layer (the device tally would no-op it
        # anyway, but each layer is a full [I, V] fused step)
        seen_exact = set()
        deduped = []
        for v in keep:
            key = (v.instance, v.validator, v.round, int(v.typ), v.value)
            if key in seen_exact:
                continue
            seen_exact.add(key)
            deduped.append(v)
        keep = deduped

        # group by (round, typ); layer repeated (instance, validator)
        groups: Dict[Tuple[int, int], List[List[WireVote]]] = \
            defaultdict(list)
        depth: Dict[Tuple[int, int, int, int], int] = defaultdict(int)
        for v in keep:
            gk = (v.round, int(v.typ))
            ck = (v.instance, v.validator, v.round, int(v.typ))
            layer = depth[ck]
            depth[ck] += 1
            layers = groups[gk]
            while len(layers) <= layer:
                layers.append([])
            layers[layer].append(v)

        phases: List[Tuple[VotePhase, int]] = []
        for (rnd, typ) in sorted(groups):
            for layer_votes in groups[(rnd, typ)]:
                slots = np.full((self.I, self.V), VOTED_NIL, np.int32)
                mask = np.zeros((self.I, self.V), bool)
                n = 0
                for v in layer_votes:
                    if v.value is None:
                        slot = VOTED_NIL
                    else:
                        s = self.slots.slot_for(v.instance, v.value)
                        if s is None:
                            self.overflow_votes.append(v)
                            continue
                        slot = s
                    slots[v.instance, v.validator] = slot
                    mask[v.instance, v.validator] = True
                    n += 1
                if n == 0:
                    continue
                phases.append((VotePhase(
                    round=jnp.full(self.I, rnd, jnp.int32),
                    typ=jnp.full(self.I, typ, jnp.int32),
                    slots=jnp.asarray(slots),
                    mask=jnp.asarray(mask),
                    height=jnp.asarray(self.heights, jnp.int32)), n))
        return phases

    def decode_slot(self, instance: int, slot: int) -> Optional[int]:
        """Device slot -> value id (for reading decisions back)."""
        if slot == NIL_ID:
            return None
        return self.slots.value_for(instance, slot)
