"""VoteBatcher: sparse signed wire votes -> dense device phases.

The ingestion path of the north star: wire votes carrying (instance,
validator, round, class, value, signature) are batch-verified (JAX
Ed25519 data plane) and densified into the [I, V] VotePhase matrices
the fused step consumes.  Votes that share an (instance, validator,
round, class) cell cannot ride one dense matrix, so the batcher
*layers* them: layer k holds each cell's k-th vote — conflicting
(equivocating) votes land in later layers and still reach the device,
where the tally's seen-record flags the double-sign.

The whole build is **vectorized numpy** (sort + run-length layering +
fancy-indexed scatter); per-vote Python only ever touches *unique new
values* (slot interning).  The array-native entry point is
`add_arrays`; `add(WireVote)` remains for sparse/test callers.  The
reference's analogue is the one-vote-at-a-time `VoteExecutor::apply`
loop (vote_executor.rs:20-23, SURVEY §3.2); this is that loop turned
into a batched device pipeline.

Window discipline (pairs with device/tally.py's rotating W-round
window; the reference tallies any round via its per-round map,
round_votes.rs:74-97):

  - FUTURE rounds (>= base+W) are *held back* and re-enter
    automatically once `sync_device` reports the rotated window.
  - PAST rounds (< base) are tallied on HOST (core.round_votes
    semantics): a late +2/3 precommit-value quorum still surfaces as a
    PRECOMMIT_VALUE event (`drain_host_events`) because
    commit-from-any-round (state_machine.rs:211) must fire no matter
    how late the quorum assembles.

Evidence: verified votes are retained per build as array batches, so a
device-side `tally.equiv` flag can be joined back to the two
conflicting *signed* votes (`signed_evidence`) — slashable proof the
reference's tally cannot produce (round_votes.rs:48-56 double-counts
instead; SURVEY §2.3 fix 2).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from agnes_tpu.bridge.value_table import MAX_VALUE_ID, SlotMap
from agnes_tpu.core.round_votes import RoundVotes, ThreshKind
from agnes_tpu.crypto.encoding import VOTE_MSG_LEN
from agnes_tpu.device.step import VotePhase
from agnes_tpu.device.tally import VOTED_NIL
from agnes_tpu.types import MAX_ROUND, NIL_ID, Vote, VoteType

_NIL = -1                 # array encoding of a nil vote's value

# packed (instance, value) pair keys: value ids are 31-bit
# (value_table.MAX_VALUE_ID), so ascending int64 order over packed
# keys == lexicographic (instance, value) order — the framework-wide
# slot interning order (C++ twin: ingest.cpp intern_ascending)
_PAIR_SHIFT = 31


def _pack_pairs(b: "_Batch") -> np.ndarray:
    """Non-nil lanes of a batch -> sorted-comparable packed keys."""
    nn = b.value >= 0
    return (b.instance[nn].astype(np.int64) << _PAIR_SHIFT) \
        | b.value[nn].astype(np.int64)


def _unpack_pair(pk: np.int64) -> Tuple[int, int]:
    return int(pk >> _PAIR_SHIFT), int(pk & ((1 << _PAIR_SHIFT) - 1))


@dataclass(frozen=True)
class WireVote:
    """One signed vote addressed to a consensus instance."""

    instance: int
    validator: int
    height: int
    round: int
    typ: VoteType
    value: Optional[int]       # None = nil
    signature: Optional[bytes] = None


@dataclass
class _Batch:
    """Column arrays for one pending/retained batch of votes."""

    instance: np.ndarray       # [N] int64
    validator: np.ndarray      # [N] int64
    height: np.ndarray         # [N] int64
    round: np.ndarray          # [N] int64
    typ: np.ndarray            # [N] int64
    value: np.ndarray          # [N] int64 (_NIL = nil)
    signature: Optional[np.ndarray]   # [N, 64] uint8 or None
    # serve-plane dedup columns (ISSUE 5; None outside cache-enabled
    # serving): `verified` marks cache-hit records whose exact bytes a
    # settled device dispatch already verified (the pipeline routes
    # them to the verify-free unsigned entries), `digest` the wire
    # SHA-256 a clean device verify inserts into the cache
    verified: Optional[np.ndarray] = None     # [N] bool
    digest: Optional[np.ndarray] = None       # [N, 32] uint8

    def __len__(self) -> int:
        return len(self.instance)

    def take(self, idx: np.ndarray) -> "_Batch":
        def opt(a):
            return a[idx] if a is not None else None

        return _Batch(
            self.instance[idx], self.validator[idx], self.height[idx],
            self.round[idx], self.typ[idx], self.value[idx],
            opt(self.signature), opt(self.verified), opt(self.digest))


def _opt_concat(batches: List[_Batch], field: str, fill) -> Optional[np.ndarray]:
    """Concat an optional column across batches (None where every
    batch lacks it; `fill(n)` pads batches that do)."""
    vals = [getattr(b, field) for b in batches]
    if all(v is None for v in vals):
        return None
    return np.concatenate([v if v is not None else fill(len(b))
                           for v, b in zip(vals, batches)])


def _concat(batches: List[_Batch]) -> _Batch:
    if len(batches) == 1:
        # no copy for the single-batch build (the hot fused path
        # concatenates once in the eligibility gate and would
        # otherwise memcpy every column again); callers never mutate
        # batch columns in place (the nil normalization rebuilds)
        return batches[0]
    sig = _opt_concat(batches, "signature",
                      lambda n: np.zeros((n, 64), np.uint8))
    ver = _opt_concat(batches, "verified", lambda n: np.zeros(n, bool))
    dig = _opt_concat(batches, "digest",
                      lambda n: np.zeros((n, 32), np.uint8))
    return _Batch(*([np.concatenate([getattr(b, f) for b in batches])
                     for f in ("instance", "validator", "height", "round",
                               "typ", "value")] + [sig, ver, dig]))


def vote_messages_np(height: np.ndarray, round_: np.ndarray,
                     typ: np.ndarray, value: np.ndarray) -> np.ndarray:
    """[N] int64 columns -> [N, 45] uint8 canonical signing messages —
    the vectorized twin of crypto.encoding.vote_signing_bytes (value
    _NIL signs the all-ones NIL_WIRE field)."""
    n = len(height)
    msg = np.zeros((n, VOTE_MSG_LEN), np.uint8)
    msg[:, 0] = (typ & 0xFF).astype(np.uint8)
    h = height.astype(np.uint64)
    for i in range(8):
        msg[:, 1 + i] = ((h >> np.uint64(8 * i))
                         & np.uint64(0xFF)).astype(np.uint8)
    r = round_.astype(np.int64).astype(np.uint32)
    for i in range(4):
        msg[:, 9 + i] = ((r >> np.uint32(8 * i))
                         & np.uint32(0xFF)).astype(np.uint8)
    nil = value == _NIL
    v = np.where(nil, 0, value).astype(np.uint64)
    for i in range(8):          # value ids are < 2^31; 8 LE bytes cover
        msg[:, 13 + i] = ((v >> np.uint64(8 * i))
                          & np.uint64(0xFF)).astype(np.uint8)
    msg[nil, 13:45] = 0xFF      # NIL_WIRE = 2^256 - 1
    return msg


def _sha_blocks_np(r_bytes: np.ndarray, a_bytes: np.ndarray,
                   msg: np.ndarray) -> np.ndarray:
    """R[N,32] || A[N,32] || M[N,45] -> [N, 1, 32] uint32 padded
    SHA-512 blocks (109 bytes + 0x80 + 16-byte bit length = 1 block),
    the vectorized twin of sha512_jax.pack_padded_host."""
    n = len(msg)
    buf = np.zeros((n, 128), np.uint8)
    buf[:, :32] = r_bytes
    buf[:, 32:64] = a_bytes
    buf[:, 64:109] = msg
    buf[:, 109] = 0x80
    bitlen = 109 * 8
    buf[:, 126] = (bitlen >> 8) & 0xFF
    buf[:, 127] = bitlen & 0xFF
    w = buf.reshape(n, 32, 4).astype(np.uint32)
    words = (w[:, :, 0] << 24) | (w[:, :, 1] << 16) \
        | (w[:, :, 2] << 8) | w[:, :, 3]
    return words.reshape(n, 1, 32)


class VoteBatcher:
    """Collects wire votes for one ingestion tick and emits dense
    phases.  One batcher per (driver, height window)."""

    def __init__(self, n_instances: int, n_validators: int, n_slots: int,
                 heights: Optional[np.ndarray] = None,
                 n_rounds: int = 4,
                 powers: Optional[np.ndarray] = None,
                 held_cap: Optional[int] = None,
                 verify_mode: str = "lanes",
                 msm_leaf: int = 64):
        self.I, self.V = n_instances, n_validators
        self.W = n_rounds
        self.slots = SlotMap(n_instances, n_slots)
        # per-instance height / window base (synced from the device)
        self.heights = np.asarray(
            heights if heights is not None
            else np.zeros(n_instances, np.int64)).astype(np.int64)
        self.base_round = np.zeros(n_instances, np.int64)
        self.powers = (np.asarray(powers, np.int64) if powers is not None
                       else np.ones(n_validators, np.int64))
        self._pending: List[_Batch] = []
        self._held: List[_Batch] = []          # future-round hold-back
        self._held_n = 0
        # the hold-back fills BEFORE signature verification, so
        # unbounded growth would be an unauthenticated memory-
        # exhaustion vector; cap at a couple of full [I, V] ticks
        # (NativeIngestLoop applies the same bound)
        if held_cap is not None and int(held_cap) <= 0:
            raise ValueError(f"held_cap must be positive: {held_cap}")
        if verify_mode not in ("lanes", "msm"):
            raise ValueError(f"verify_mode must be lanes|msm: {verify_mode}")
        # "lanes" = per-lane verification; "msm" = the batch
        # random-linear-combination fast path with per-lane bisection
        # fallback on any failure (crypto/msm_jax.py).  Both apply the
        # framework's cofactored policy, so verdicts are identical —
        # the mode is purely a throughput choice.
        self.verify_mode = verify_mode
        if int(msm_leaf) < 2:
            # leaf 1 would make the adaptive bisection midpoint
            # degenerate (lo + n//2 == lo) on a failing lane
            raise ValueError(f"msm_leaf must be >= 2: {msm_leaf}")
        self.msm_leaf = int(msm_leaf)
        self.held_cap = (int(held_cap) if held_cap is not None
                         else max(65536, 2 * self.I * self.V))
        self._log: List[_Batch] = []           # verified votes (evidence)
        # device-verify build state (build_phases_device): pubkeys for
        # the fallback-subset host checks + lane batches aligned with
        # the emitted phases.  NOTE in device-verify mode _log entries
        # are pre-verdict — evidence consumers verify the signatures
        # they extract (they carry them; slashing must anyway).
        self._dv_pubkeys: Optional[np.ndarray] = None
        self._emitted_lane_groups: List[_Batch] = []
        # (digest [N,32], instance [N], height [N]) of the real lanes
        # the LAST device-verify build emitted (None when the build had
        # no digest column or fell back host-verified): the serve
        # pipeline snapshots this per staged build and inserts the keys
        # into the dedup cache once that dispatch's verify settles with
        # zero rejected lanes (cache.py's poisoning-safety contract)
        self.last_build_keys: Optional[Tuple] = None
        # per-_log-entry pubkey table: None = logged post-screen
        # (host-verified/unsigned build, nothing to re-check); an
        # array = the device-verify build's epoch table to re-verify
        # evidence candidates against
        self._log_pk: List[Optional[np.ndarray]] = []
        self.rejected_signature = 0
        self.rejected_malformed = 0
        self.overflow_votes = 0
        self.dropped_stale_height = 0
        self.dropped_held_overflow = 0
        # host fallback tallies for past (rotated-out) rounds
        self._host_tally: Dict[Tuple[int, int], RoundVotes] = {}
        self._host_events: List[Tuple[int, int, int]] = []

    def set_validators(self, powers: np.ndarray) -> None:
        """Validator-set epoch (reference validators.rs:38-46 intent,
        SURVEY §2.6): adopt new voting powers AT A HEIGHT BOUNDARY —
        call right after the sync_device that advanced heights (which
        dropped the old heights' host tallies).  A power of 0 models
        removal; the pubkey table is per-build (`build_phases(pubkeys)`)
        so key rotation needs no call here."""
        pw = np.asarray(powers, np.int64)
        if pw.shape != (self.V,):
            raise ValueError(f"powers must be [{self.V}], got {pw.shape}")
        self.powers = pw

    # -- enqueue -------------------------------------------------------------

    def add_arrays(self, instance, validator, height, round_, typ, value,
                   signatures: Optional[np.ndarray] = None,
                   verified: Optional[np.ndarray] = None,
                   digest: Optional[np.ndarray] = None) -> None:
        """Bulk enqueue: [N] integer arrays (+ optional [N, 64] uint8
        signatures).  value < 0 means nil.  This is the fast path — no
        per-vote Python objects anywhere.  `verified`/`digest` are the
        serve dedup columns (queue.WireColumns): a [N] bool cache-hit
        mask and the [N, 32] wire SHA-256s; they ride the pending/held
        queues so the pipeline's split-rung dispatch can separate
        pre-verified re-deliveries from fresh traffic."""
        self._pending.append(_Batch(
            np.asarray(instance, np.int64), np.asarray(validator, np.int64),
            np.asarray(height, np.int64), np.asarray(round_, np.int64),
            np.asarray(typ, np.int64),
            np.asarray(value, np.int64),
            np.asarray(signatures, np.uint8)
            if signatures is not None else None,
            np.asarray(verified, bool) if verified is not None else None,
            np.asarray(digest, np.uint8) if digest is not None else None))

    def add_class_votes(self, instance, validator, height, round_,
                        typ, value) -> None:
        """Enqueue a batch of PRE-VERIFIED class votes (ISSUE 10: the
        BLS aggregate lane's cleared/fallback-verified shares).  The
        mixed-mode rung: a whole aggregate class enters as rows that
        densify to ONE dense phase carrying the class's combined
        voting weight (each signer's mask bit applies that signer's
        power in the tally — leaf-identical to per-vote ingestion),
        and the verified flag routes the build down the verify-free
        unsigned entries via the split-rung dispatch.  Callers must
        have a cleared pairing (or per-share fallback verify) behind
        every row — the cache-hit contract, extended to the lane."""
        n = len(np.asarray(instance))
        self.add_arrays(instance, validator, height, round_, typ,
                        value, verified=np.ones(n, bool))

    def add(self, vote: WireVote) -> None:
        if vote.signature is not None and len(vote.signature) != 64:
            # wrong-length signatures can't ride the [N, 64] column;
            # screen here (one hostile vote must not DoS the tick)
            self.rejected_malformed += 1
            return
        sig = (np.frombuffer(vote.signature, np.uint8)[None, :]
               if vote.signature is not None else None)
        self.add_arrays([vote.instance], [vote.validator], [vote.height],
                        [vote.round], [int(vote.typ)],
                        [_NIL if vote.value is None else vote.value], sig)

    def extend(self, votes) -> None:
        for v in votes:
            self.add(v)

    # -- device sync ---------------------------------------------------------

    def sync_device(self, base_round, heights) -> None:
        """Adopt the device plane's rotated window bases and heights
        (call after each step when rotation/height-advance are live).
        Held future-round votes whose window arrived re-enter the
        pending queue; a height advance resets that instance's slots."""
        new_heights = np.asarray(heights, np.int64)
        advanced = np.nonzero(new_heights > self.heights)[0]
        for i in advanced:
            self.slots.reset_instance(int(i))
        if len(advanced):
            adv = set(int(i) for i in advanced)
            # decided heights can never commit again: drop their host
            # tallies (and never mix them into newer heights' quorums)
            self._host_tally = {
                k: v for k, v in self._host_tally.items()
                if not (k[0] in adv and k[1] < new_heights[k[0]])}
        self.heights = new_heights
        self.base_round = np.asarray(base_round, np.int64)
        if self._held:
            held, self._held = self._held, []
            self._held_n = 0
            self._pending.extend(held)

    def clear_log(self) -> None:
        """Drop retained evidence batches (extract evidence for flagged
        validators via `signed_evidence` first)."""
        self._log = []

    @property
    def held_votes(self) -> int:
        """Future-round votes currently held back (they re-enter on the
        sync_device that rotates their window in; the serve plane's
        drain reports what is still held at shutdown)."""
        return self._held_n

    @property
    def pending_votes(self) -> int:
        """Votes enqueued but not yet drained by a build."""
        return sum(len(b) for b in self._pending)

    def split_pending_verified(self) -> List[_Batch]:
        """Remove the PRE-VERIFIED rows (serve dedup-cache hits; the
        `verified` column) from the pending queue and return them as
        their own batch list, arrival order preserved within each
        stream.  The serve pipeline's split-rung dispatch builds the
        remaining fresh rows through the signed device-verify path,
        then feeds the returned batches back via `adopt_pending` and
        builds them UNSIGNED — the partition must happen here, at the
        queue level, because held future-round votes re-enter
        `_pending` carrying their flag and a fresh (unverified) vote
        must never ride an unsigned build."""
        pre: List[_Batch] = []
        fresh: List[_Batch] = []
        for b in self._pending:
            v = b.verified
            if v is None or not v.any():
                fresh.append(b)
            elif v.all():
                pre.append(b)
            else:
                pre.append(b.take(np.nonzero(v)[0]))
                fresh.append(b.take(np.nonzero(~v)[0]))
        self._pending = fresh
        return pre

    def adopt_pending(self, batches: List[_Batch]) -> None:
        """Re-queue batches returned by `split_pending_verified`."""
        self._pending.extend(batches)

    # -- signature verification ----------------------------------------------

    def _pack_verify_inputs_np(self, b: _Batch, pubkeys: np.ndarray):
        """Numpy (pub, sig, blocks) Ed25519 verify-kernel inputs for a
        batch — the ONE packing recipe, shared by the host-side
        _verify, the device-fused lane packer and the dense builder so
        the paths cannot desync (and so dense scattering never has to
        fetch freshly uploaded device arrays back to the host)."""
        msg = vote_messages_np(b.height, b.round, b.typ, b.value)
        a_bytes = np.asarray(pubkeys)[b.validator]        # [N, 32]
        sig = (b.signature if b.signature is not None
               else np.zeros((len(b), 64), np.uint8))
        return (a_bytes.astype(np.int32), sig.astype(np.int32),
                _sha_blocks_np(sig[:, :32], a_bytes, msg))

    def _pack_verify_inputs(self, b: _Batch, pubkeys: np.ndarray):
        pub, sig, blocks = self._pack_verify_inputs_np(b, pubkeys)
        return jnp.asarray(pub), jnp.asarray(sig), jnp.asarray(blocks)

    def _verify(self, b: _Batch, pubkeys: np.ndarray) -> np.ndarray:
        """Batch-verify on the JAX plane; pubkeys [V, 32] uint8 is the
        device-resident validator table (ValidatorSet.device_arrays).
        Returns [N] bool."""
        from agnes_tpu.crypto import ed25519_jax as ejax

        pub, sig, blocks = self._pack_verify_inputs(b, pubkeys)
        if self.verify_mode == "msm":
            from agnes_tpu.crypto import msm_jax
            return msm_jax.verify_batch_adaptive(pub, sig, blocks,
                                                 leaf=self.msm_leaf)
        return np.asarray(ejax.verify_batch_jit(pub, sig, blocks))

    # -- host fallback for past rounds ---------------------------------------

    def _host_tally_screened(self, b: _Batch) -> None:
        """Route votes to the host tally.  In device-verify mode they
        must be verified HERE first: the bulk verdicts are computed
        fused on device (consensus_step_seq_signed) and never reach
        the host buckets, so an unscreened spill would let forged
        votes into the fallback tally."""
        if self._dv_pubkeys is not None and len(b):
            good = self._verify(b, self._dv_pubkeys)
            self.rejected_signature += int(len(b) - good.sum())
            b = b.take(np.nonzero(good)[0])
        if len(b):
            self._host_tally_past(b)

    def _host_tally_past(self, b: _Batch) -> None:
        """Tally rotated-out rounds with the host RoundVotes (exact
        core semantics: per-value buckets, dedup, evidence).  Only the
        commit-critical threshold is surfaced: +2/3 precommit-value at
        ANY round decides (state_machine.rs:211)."""
        total = int(self.powers.sum())
        for k in range(len(b)):
            inst, hgt, rnd = (int(b.instance[k]), int(b.height[k]),
                              int(b.round[k]))
            # keyed by height too: a tally must never mix votes from
            # different heights into one quorum
            rv = self._host_tally.get((inst, hgt, rnd))
            if rv is None:
                rv = RoundVotes(height=hgt, round=rnd, total=total)
                self._host_tally[(inst, hgt, rnd)] = rv
            val = None if b.value[k] == _NIL else int(b.value[k])
            thresh = rv.add_vote(
                Vote(typ=VoteType(int(b.typ[k])), round=rnd, value=val,
                     height=hgt, validator=int(b.validator[k])),
                int(self.powers[b.validator[k]]))
            if (int(b.typ[k]) == int(VoteType.PRECOMMIT)
                    and thresh.kind == ThreshKind.VALUE):
                self._host_events.append((inst, hgt, rnd, thresh.value))

    def drain_host_events(self) -> List[Tuple[int, int, int, int]]:
        """[(instance, height, round, value_id)] late precommit-value
        quorums detected by the host fallback; the driver injects these
        as PRECOMMIT_VALUE ext events (commit-from-any-round) iff the
        instance is still at that height."""
        ev, self._host_events = self._host_events, []
        return ev

    # -- densification -------------------------------------------------------

    def _defer_pending(self, max_votes: Optional[int]) -> List[_Batch]:
        """Cap the NEXT build at `max_votes` pending votes (arrival
        order — a straddling batch splits), returning the deferred
        tail for the caller to restore into `_pending` after the
        build.  This is the serve plane's window-aware split: a held
        future-round burst re-entering on the sync that rotated its
        window in lands in `_pending` ALONGSIDE the fresh tick, and an
        uncapped build would drain both into one lane shape above the
        ladder's top rung — a live compile stall (the ISSUE-2
        `offladder_builds` leak).  Capped, the burst and the tick
        build separately, each onto a warmed rung."""
        if max_votes is None:
            return []
        if int(max_votes) <= 0:
            raise ValueError(f"max_votes must be positive: {max_votes}")
        left = int(max_votes)
        head: List[_Batch] = []
        tail: List[_Batch] = []
        for b in self._pending:
            if left <= 0:
                tail.append(b)
            elif len(b) <= left:
                head.append(b)
                left -= len(b)
            else:
                head.append(b.take(np.arange(left)))
                tail.append(b.take(np.arange(left, len(b))))
                left = 0
        self._pending = head
        return tail

    def build_phases(self, pubkeys: Optional[np.ndarray] = None,
                     _device_verify: bool = False,
                     max_votes: Optional[int] = None
                     ) -> List[Tuple[VotePhase, int]]:
        """Drain pending votes into dense phases.

        Returns [(phase, n_votes)], one per (round, class, layer),
        deterministic order.  With `pubkeys` given, signatures are
        batch-verified first and failures dropped (and counted).
        `_device_verify` (internal; use build_phases_device) defers the
        bulk verification to the device-fused step — only the
        host-fallback subsets (past rounds, slot spill) verify here,
        because their tallies happen host-side where device verdicts
        never arrive.  `max_votes` caps the build at the oldest
        `max_votes` pending votes; the rest stay pending for the next
        build (_defer_pending — the serve plane's ladder-cap split)."""
        if max_votes is not None:
            tail = self._defer_pending(max_votes)
            try:
                return self.build_phases(pubkeys,
                                         _device_verify=_device_verify)
            finally:
                if tail:
                    self._pending.extend(tail)
        if not self._pending:
            return []
        b, self._pending = _concat(self._pending), []
        n0 = len(b)
        if n0 == 0:
            return []

        # --- malformed screen (vectorized; typ outside {0,1} would
        # alias into the wrong (round, class) group downstream)
        ok = ((b.instance >= 0) & (b.instance < self.I)
              & (b.validator >= 0) & (b.validator < self.V)
              & (b.round >= 0) & (b.round <= MAX_ROUND)
              & (b.typ >= 0) & (b.typ <= 1)
              & (b.value <= MAX_VALUE_ID))
        self.rejected_malformed += int(n0 - ok.sum())
        # height gate: votes for other heights than the instance's are
        # stale (or early); counted separately from malformed
        inst_c = np.clip(b.instance, 0, self.I - 1)
        h_ok = b.height == self.heights[inst_c]
        self.dropped_stale_height += int((ok & ~h_ok).sum())
        b = b.take(np.nonzero(ok & h_ok)[0])
        if len(b) == 0:
            return []
        # normalize the nil encoding (contract: any value < 0 is nil).
        # Rebuild rather than mutate: batch columns can alias caller
        # arrays (add_arrays is zero-copy) via _concat's 1-batch path.
        if (b.value < _NIL).any():
            b = replace(b, value=np.where(b.value < 0, _NIL, b.value))

        # --- hold back future rounds BEFORE verification: they are
        # verified (and logged) once, when the window reaches them —
        # not once per tick they sit in the queue
        widx = b.round - self.base_round[b.instance]
        future = widx >= self.W
        if future.any():
            fut = np.nonzero(future)[0]
            room = self.held_cap - self._held_n
            if len(fut) > room:           # cap: fail closed, count
                self.dropped_held_overflow += len(fut) - max(room, 0)
                fut = fut[:max(room, 0)]
            if len(fut):
                self._held.append(b.take(fut))
                self._held_n += len(fut)
            b = b.take(np.nonzero(~future)[0])
            if len(b) == 0:
                return []

        # --- signature verification (batched, one kernel call).  When
        # pubkeys are supplied, unsigned votes must FAIL, not bypass:
        # missing signature columns verify as zero signatures.  In
        # device-verify mode the bulk check runs fused inside the step
        # dispatch instead (consensus_step_seq_signed) — only the
        # host-tallied subsets below verify here.
        self._dv_pubkeys = pubkeys if _device_verify else None
        if pubkeys is not None:
            if b.signature is None:
                b = replace(b, signature=np.zeros((len(b), 64),
                                                  np.uint8))
            if not _device_verify:
                good = self._verify(b, pubkeys)
                self.rejected_signature += int(len(b) - good.sum())
                b = b.take(np.nonzero(good)[0])
                if len(b) == 0:
                    return []

        # --- retain votes for slashable evidence.  Host-verified and
        # unsigned builds log post-screen; device-verify builds log
        # PRE-verdict, so the build's pubkey table rides along
        # (_log_pk) and signed_evidence re-verifies against exactly
        # that epoch (key-rotation safe) before trusting an entry.
        self._log.append(b)
        self._log_pk.append(self._dv_pubkeys)

        # --- past (rotated-out) rounds go to the host tally
        past = (b.round - self.base_round[b.instance]) < 0
        if past.any():
            self._host_tally_screened(b.take(np.nonzero(past)[0]))
            b = b.take(np.nonzero(~past)[0])
            if len(b) == 0:
                return []

        # --- fast path: one round, each class's (instance, validator)
        # cells occupied at most once — the common shapes (a gossip
        # tick of one phase's honest votes, or both classes of a round
        # batched into one build for a single 2n-lane verify).  O(n)
        # bincount checks, no sorts; classes emit in (prevote,
        # precommit) order, matching the general path's sort order.
        if (b.round[0] == b.round).all():
            parts = []
            for t in (int(VoteType.PREVOTE), int(VoteType.PRECOMMIT)):
                m = b.typ == t
                if not m.any():
                    continue
                sub = b.take(np.nonzero(m)[0])
                cell_id = sub.instance * self.V + sub.validator
                counts = np.bincount(cell_id, minlength=self.I * self.V)
                if (counts > 1).any():
                    parts = None
                    break
                parts.append(sub)
            if parts is not None:
                # with BOTH classes present AND carrying different
                # values, intern new (instance, value) pairs in one
                # combined ascending pass first — matching the general
                # path's np.unique order and the C++ fast path's
                # intern_ascending — so slot numbering never depends on
                # class processing order (mixed-value two-class builds
                # diverged before: prevote values grabbed slots ahead
                # of smaller precommit values).  Slot maps are
                # per-instance, so order can only diverge when one
                # instance sees >= 2 distinct new values in the build —
                # impossible single-class (np.unique order inside
                # _intern_slots) or when both classes carry the same
                # single value (the steady-state honest tick, gated
                # O(n) by min==max so it pays no sort here).
                if len(parts) > 1:
                    monos = []
                    for sub in parts:
                        nn = sub.value[sub.value >= 0]
                        if len(nn):
                            lo, hi = nn.min(), nn.max()
                            monos.append(int(lo) if lo == hi else -1)
                    if -1 in monos or len(set(monos)) > 1:
                        packed = [_pack_pairs(sub) for sub in parts]
                        packed = [p for p in packed if len(p)]
                        for pk in np.unique(np.concatenate(packed)):
                            self.slots.prealloc(*_unpack_pair(pk))
                groups = []
                for sub in parts:
                    sub, slot = self._intern_and_spill(sub)
                    if len(sub):
                        groups.append((sub, slot, int(sub.round[0]),
                                       int(sub.typ[0])))
                return self._emit(groups) if groups else []

        # --- general path: ONE lexsort orders everything; duplicates,
        # layers and phase groups all fall out of adjacency scans.
        # Sorting (value, arrival) last makes equal-value redeliveries
        # adjacent within their cell — exact dedup with no second sort.
        arrival = np.arange(len(b))
        order = np.lexsort((arrival, b.value, b.validator, b.instance,
                            b.typ, b.round))
        bs = b.take(order)

        def cell_runs(x: _Batch) -> np.ndarray:
            return ((x.round[1:] == x.round[:-1])
                    & (x.typ[1:] == x.typ[:-1])
                    & (x.instance[1:] == x.instance[:-1])
                    & (x.validator[1:] == x.validator[:-1]))

        same_cell = cell_runs(bs)
        dup = np.zeros(len(bs), bool)
        dup[1:] = same_cell & (bs.value[1:] == bs.value[:-1])
        if dup.any():
            bs = bs.take(np.nonzero(~dup)[0])
            same_cell = cell_runs(bs)
        n = len(bs)

        # layer = rank within the (still sorted) cell run
        new_cell = np.ones(n, bool)
        new_cell[1:] = ~same_cell
        group_start = np.maximum.accumulate(
            np.where(new_cell, np.arange(n), 0))
        layer = np.arange(n) - group_start

        bs, slot, layer = self._intern_and_spill(bs, layer)
        if len(bs) == 0:
            return []

        # group into phases by packed (round, typ, layer) int64 key
        pkey = ((bs.round.astype(np.int64) << 22)
                | (bs.typ.astype(np.int64) << 21)
                | np.minimum(layer, (1 << 21) - 1))
        ukeys, pinv = np.unique(pkey, return_inverse=True)
        groups = []
        for p, k in enumerate(ukeys):
            sel = np.nonzero(pinv == p)[0]
            groups.append((bs.take(sel), slot[sel],
                           int(k >> 22), int((k >> 21) & 1)))
        return self._emit(groups)

    def _device_verify_eligible(self) -> bool:
        """Gate for the device-fused build: the pending traffic must be
        the honest dense shape — ONE round, each (class, instance,
        validator) cell at most once, and at most ONE distinct non-nil
        value per instance.  Anything else (multi-value builds, dedup
        layers) is where unauthenticated traffic could pollute
        host-side state BEFORE device verdicts exist — slot interning
        and layer densification happen on the host — so those builds
        take the host-verified path instead (forged votes are then
        dropped before they can touch slots or mint phases).

        Residual exposure, accepted + documented: an attacker pacing
        forged single-value builds can still intern one value per
        build; exhausting an instance's S slots that way degrades
        honest traffic to the (verified, benchmarked) host-fallback
        tally — the same cliff as the value-flood attack — and never
        affects safety, since forged votes are masked before tallying
        on every path.  Under active flood, run the host-verified
        mode (RunConfig verify_mode/path selection)."""
        if not self._pending:
            return False
        b = _concat(self._pending)
        self._pending = [b]            # keep the concat for the build
        if len(b) == 0 or (b.round != b.round[0]).any():
            return False
        # unique (class, instance, validator) cells, hostile-index safe
        if ((b.typ < 0) | (b.typ > 1) | (b.instance < 0)
                | (b.instance >= self.I) | (b.validator < 0)
                | (b.validator >= self.V)).any():
            return False
        cell = ((b.typ * self.I + b.instance) * self.V + b.validator)
        if (np.bincount(cell, minlength=2 * self.I * self.V) > 1).any():
            return False
        # <= 1 distinct non-nil value per instance
        nn = b.value >= 0
        if nn.any():
            lo = np.full(self.I, np.iinfo(np.int64).max, np.int64)
            hi = np.full(self.I, -1, np.int64)
            np.minimum.at(lo, b.instance[nn], b.value[nn])
            np.maximum.at(hi, b.instance[nn], b.value[nn])
            if ((hi >= 0) & (lo != hi)).any():
                return False
        return True

    def build_phases_device(self, pubkeys: np.ndarray,
                            phase_offset: int = 0,
                            lane_floor: int = 0,
                            max_votes: Optional[int] = None):
        """Drain pending votes into dense phases with verification
        deferred to the DEVICE: returns (phases, SignedLanes) where the
        lanes carry every emitted vote's packed Ed25519 inputs, keyed
        to its phase index (+ `phase_offset`, for callers that prepend
        e.g. an entry phase to the step sequence).  Feed both to
        DeviceDriver.step_seq_signed — verification runs FUSED in the
        step dispatch and its verdicts mask the phases on device, so
        no device->host verdict sync separates densify from tally
        (SURVEY §3.2's single fused kernel; the host-verified
        build_phases path remains for mesh drivers and as the
        measured-overhead baseline).

        Falls back to the HOST-verified build — returning (phases,
        None); drive those with step()/step_seq — whenever the traffic
        is not the honest dense shape (_device_verify_eligible) or the
        batcher is in MSM mode (the fused kernel is per-lane).
        Host-fallback subsets (past rounds, slot spill) are always
        verified host-side — their tallies live in host buckets where
        device verdicts never arrive.  rejected_signature counts those
        host checks; device rejections surface via the driver's
        rejected_signature_device.

        Lanes are padded up to the next power of two with copies of
        lane 0 aimed at an out-of-range phase (scatter-dropped on
        device; a copy of a valid lane cannot inflate n_rejected) so
        variable per-tick vote counts reuse a logarithmic number of
        compiled (P, N) shapes instead of recompiling the fused step
        per tick.  `lane_floor` raises that padding to at least the
        given lane count (pass a serve ShapeLadder rung — itself a
        power of two — so small micro-batches all land on ONE
        precompiled shape instead of one per log2(n)).  `max_votes`
        caps the build (oldest first; _defer_pending) so one build can
        never exceed a serve ladder's top rung."""
        phases, cat, pidx = self._build_device_common(pubkeys,
                                                      max_votes=max_votes)
        if cat is None:
            return phases, None
        phase_idx = pidx + phase_offset
        n = len(cat)
        n_pad = max(1 << (n - 1).bit_length(), int(lane_floor))
        real = np.ones(n_pad, bool)
        if n_pad > n:
            real[n:] = False
            fill = np.zeros(n_pad - n, np.intp)      # copies of lane 0
            cat = _concat([cat, cat.take(fill)])
            phase_idx = np.concatenate(
                [phase_idx,
                 np.full(n_pad - n, phase_offset + len(phases), np.int64)])
        pub, sig, blocks = self._pack_verify_inputs(cat, pubkeys)
        from agnes_tpu.device.step import SignedLanes
        lanes = SignedLanes(
            pub=pub, sig=sig, blocks=blocks,
            phase_idx=jnp.asarray(phase_idx, jnp.int32),
            inst=jnp.asarray(cat.instance, jnp.int32),
            val=jnp.asarray(cat.validator, jnp.int32),
            real=jnp.asarray(real))
        return phases, lanes

    def _build_device_common(self, pubkeys: np.ndarray,
                             max_votes: Optional[int] = None):
        """Shared device-verify build core: (phases, cat, phase_idx)
        with 0-based numpy phase indices, or (host-verified phases,
        None, None) on the fallback paths (ineligible traffic, MSM
        mode, or an all-host-fallback build).  `max_votes` defers the
        pending tail BEFORE the eligibility gate, so eligibility is
        judged on exactly the votes this build will drain (a capped
        burst must not be declared ineligible by traffic that builds
        separately after it)."""
        tail = self._defer_pending(max_votes)
        self.last_build_keys = None
        # digest integrity is all-or-none across the batches this
        # build drains: _concat zero-fills a missing optional column,
        # and a zero digest must NEVER become a "verified" cache key —
        # fail closed by withholding keys from mixed builds
        all_digests = bool(self._pending) and all(
            b.digest is not None for b in self._pending)
        try:
            if (self.verify_mode != "lanes"
                    or not self._device_verify_eligible()):
                return self.build_phases(pubkeys), None, None
            self._emitted_lane_groups = []
            phases = self.build_phases(pubkeys, _device_verify=True)
            groups, self._emitted_lane_groups = \
                self._emitted_lane_groups, []
            self._dv_pubkeys = None
            if not phases:
                return [], None, None
            assert len(groups) == len(phases)
            cat = _concat(groups)
            phase_idx = np.concatenate([np.full(len(g), i, np.int64)
                                        for i, g in enumerate(groups)])
            if all_digests and cat.digest is not None:
                # dedup-cache insertion keys for exactly the emitted
                # real lanes (pre-padding): screened/stale/held rows
                # never became lanes, so they never become cache keys
                self.last_build_keys = (cat.digest, cat.instance,
                                        cat.height)
            return phases, cat, phase_idx
        finally:
            if tail:
                self._pending.extend(tail)

    def build_phases_device_dense(self, pubkeys: np.ndarray,
                                  max_votes: Optional[int] = None):
        """build_phases_device in the DENSE lane layout that shards
        under shard_map (device/step.py DenseSignedPhases): returns
        (phases, DenseSignedPhases) with sig/blocks scattered to
        [Ps, I, V, ...] — feed to DeviceDriver.step_seq_signed_dense
        (single chip or mesh).  Cells without a vote hold zeros and
        verify False, which the mask AND discards.  Same eligibility
        gate and host-fallback screening as build_phases_device; falls
        back to (host-verified phases, None) identically.  The scatter
        stays entirely in numpy (one device upload at the end — never
        a fetch of freshly uploaded lane arrays)."""
        phases, cat, pidx = self._build_device_common(pubkeys,
                                                      max_votes=max_votes)
        if cat is None:
            return phases, None
        from agnes_tpu.device.step import DenseSignedPhases

        Ps = len(phases)
        _, sig_np, blocks_np = self._pack_verify_inputs_np(cat, pubkeys)
        sig = np.zeros((Ps, self.I, self.V, 64), np.int32)
        blocks = np.zeros((Ps, self.I, self.V) + blocks_np.shape[1:],
                          blocks_np.dtype)
        sig[pidx, cat.instance, cat.validator] = sig_np
        blocks[pidx, cat.instance, cat.validator] = blocks_np
        dense = DenseSignedPhases(
            pub=jnp.asarray(np.asarray(pubkeys).astype(np.int32)),
            sig=jnp.asarray(sig), blocks=jnp.asarray(blocks))
        return phases, dense

    def adopt_native_phases(self, cols, ph, pubkeys: np.ndarray):
        """Adopt a NATIVE phase drain (ISSUE 20 zero-copy densify):
        `cols` is the drained WireColumns batch and `ph` the
        NativePhases bundle core/native/admission_phases.cpp filled for
        it — the exact arrays build_phases_device would have produced
        for these rows against this window (the native side bails to a
        plain drain on ANY case where the Python build would drop,
        split, intern or multi-phase, so adoption is only ever offered
        for the no-op-screen single-round fast path).  Returns
        (phases, SignedLanes) with every device array wrapped by ONE
        jnp.asarray — no per-record Python work.

        What this method still owes the Python build, per-batch not
        per-record:

        * the evidence log entry — the ARRIVAL-order batch with the
          nil encoding normalized, plus the build's pubkey epoch table
          (device-verify builds log pre-verdict; signed_evidence
          re-verifies against exactly this table)
        * last_build_keys — the dedup-cache insertion keys of the real
          lanes in the build's PHASE-GROUPED cat order (`ph.lane_rows`
          is the native side's lane -> drained-row permutation)

        The caller (ServePipeline.stage) owns the preconditions: no
        other pending votes (the build must drain exactly `cols`), and
        ph.heights/base_round equal to the batcher's post-sync window
        (native_phase_state predicted it; a rotation between drain and
        stage falls back to add_arrays on the plain columns)."""
        value = cols.value
        if (value < _NIL).any():
            value = np.where(value < 0, _NIL, value)
        b = _Batch(cols.instance, cols.validator, cols.height,
                   cols.round_, cols.typ, value, cols.signatures,
                   np.asarray(cols.verified, bool), cols.digest)
        pk = np.asarray(pubkeys)
        self._log.append(b)
        self._log_pk.append(pk)
        rows = ph.lane_rows
        if cols.digest is not None:
            self.last_build_keys = (cols.digest[rows],
                                    cols.instance[rows],
                                    cols.height[rows])
        else:
            self.last_build_keys = None
        hts = jnp.asarray(self.heights.astype(np.int32))
        phases = [(VotePhase(
            round=jnp.full(self.I, int(ph.round_), jnp.int32),
            typ=jnp.full(self.I, int(ph.typ[p]), jnp.int32),
            slots=jnp.asarray(ph.slots[p]),
            mask=jnp.asarray(ph.mask[p]),
            height=hts), int(ph.counts[p]))
            for p in range(ph.n_phases)]
        from agnes_tpu.device.step import SignedLanes
        lanes = SignedLanes(
            pub=jnp.asarray(ph.pub), sig=jnp.asarray(ph.sig),
            blocks=jnp.asarray(ph.blocks),
            phase_idx=jnp.asarray(ph.phase_idx),
            inst=jnp.asarray(ph.inst), val=jnp.asarray(ph.val),
            real=jnp.asarray(ph.real))
        return phases, lanes

    def _intern_and_spill(self, b: _Batch, layer: Optional[np.ndarray] = None):
        """Intern slots; votes whose value overflows the instance's
        slot budget spill to the HOST tally (SlotMap's documented
        fallback for many-value floods) so a quorum on an untracked
        value still commits via drain_host_events.  Returns the kept
        batch + slots (+ layers when given)."""
        slot = self._intern_slots(b)
        ovf = slot == VOTED_NIL - 1
        if ovf.any():
            self._host_tally_screened(b.take(np.nonzero(ovf)[0]))
            keep = np.nonzero(~ovf)[0]
            b, slot = b.take(keep), slot[~ovf]
            if layer is not None:
                layer = layer[~ovf]
        return (b, slot) if layer is None else (b, slot, layer)

    def _intern_slots(self, b: _Batch) -> np.ndarray:
        """[N] slot per vote (VOTED_NIL for nil, VOTED_NIL-1 for
        overflow); python only over UNIQUE new (instance, value)."""
        slot = np.full(len(b), VOTED_NIL, np.int64)
        nonnil = b.value >= 0
        if nonnil.any():
            nn = np.nonzero(nonnil)[0]
            if (b.value[nn] == b.value[nn[0]]).all():
                # single proposal value (the common case): unique pairs
                # are just the distinct instances; map via an array LUT
                uinst = np.unique(b.instance[nn])
                v0 = int(b.value[nn[0]])
                lut = np.full(self.I, VOTED_NIL - 1, np.int64)
                for inst in uinst:
                    s = self.slots.slot_for(int(inst), v0)
                    lut[inst] = VOTED_NIL - 1 if s is None else s
                slot[nn] = lut[b.instance[nn]]
            else:
                pair = _pack_pairs(b)
                upairs, inv = np.unique(pair, return_inverse=True)
                uslots = np.empty(len(upairs), np.int64)
                for j, pk in enumerate(upairs):
                    s = self.slots.slot_for(*_unpack_pair(pk))
                    uslots[j] = VOTED_NIL - 1 if s is None else s
                slot[nn] = uslots[inv]
        ovf = int((slot == VOTED_NIL - 1).sum())
        self.overflow_votes += ovf
        return slot

    def _emit(self, groups) -> List[Tuple[VotePhase, int]]:
        """[(batch, slot, round, typ)] -> dense VotePhases (fancy-index
        scatter; no per-vote python).  In device-verify mode the
        per-phase lane batches are retained (aligned with the emitted
        phase order) for build_phases_device to pack."""
        hts = jnp.asarray(self.heights.astype(np.int32))
        phases: List[Tuple[VotePhase, int]] = []
        for bg, sg, rnd, typ in groups:
            keep = sg != VOTED_NIL - 1
            if not keep.all():
                idx = np.nonzero(keep)[0]
                bg, sg = bg.take(idx), sg[idx]
            if len(bg) == 0:
                continue
            if self._dv_pubkeys is not None:
                self._emitted_lane_groups.append(bg)
            slots = np.full((self.I, self.V), VOTED_NIL, np.int32)
            mask = np.zeros((self.I, self.V), bool)
            slots[bg.instance, bg.validator] = sg
            mask[bg.instance, bg.validator] = True
            phases.append((VotePhase(
                round=jnp.full(self.I, rnd, jnp.int32),
                typ=jnp.full(self.I, typ, jnp.int32),
                slots=jnp.asarray(slots),
                mask=jnp.asarray(mask),
                height=hts), int(len(bg))))
        return phases

    # -- evidence ------------------------------------------------------------

    def signed_evidence(self, instance: int, validator: int
                        ) -> Optional[Tuple[WireVote, WireVote]]:
        """Join a device equivocation flag back to the two conflicting
        *signed* votes: scans the retained batches for two votes by
        `validator` in `instance` with the same (height, round, class)
        and different values.  Returns (first, second) WireVotes whose
        signatures prove the double-sign to any third party, or None.

        Batches logged by device-verify builds are PRE-verdict — a
        forged vote could otherwise shadow a real provable pair (or
        fabricate an unprovable one) — so their candidate votes are
        re-verified here against the pubkey table OF THAT BUILD
        (key-rotation safe; _log_pk) in one batched call per logged
        build, and unverifiable votes are skipped.  Host-verified and
        unsigned builds logged post-screen and are trusted as
        before."""
        seen: Dict[Tuple[int, int, int], Tuple[int, Optional[bytes]]] = {}
        for bi, batch in enumerate(self._log):
            hit = np.nonzero((batch.instance == instance)
                             & (batch.validator == validator))[0]
            if len(hit) == 0:
                continue
            pk = self._log_pk[bi] if bi < len(self._log_pk) else None
            if pk is not None:
                if batch.signature is None:
                    continue
                good = np.asarray(self._verify(batch.take(hit), pk))
                hit = hit[good.astype(bool)]
            for k in hit:
                key = (int(batch.height[k]), int(batch.round[k]),
                       int(batch.typ[k]))
                val = int(batch.value[k])
                sig = (batch.signature[k].tobytes()
                       if batch.signature is not None else None)
                if key not in seen:
                    seen[key] = (val, sig)
                elif seen[key][0] != val:
                    h, r, t = key
                    fv, fsig = seen[key]

                    def mk(v, s):
                        return WireVote(
                            instance=instance, validator=validator,
                            height=h, round=r, typ=VoteType(t),
                            value=None if v == _NIL else v, signature=s)

                    return mk(fv, fsig), mk(val, sig)
        return None

    def decode_slot(self, instance: int, slot: int) -> Optional[int]:
        """Device slot -> value id (for reading decisions back)."""
        if slot == NIL_ID:
            return None
        return self.slots.value_for(instance, slot)
