"""Join device equivocation flags to slashable signed evidence.

The device tally flags double-signers as a dense [instances,
validators] bool plane (device/tally.py `equiv` — the per-validator
seen-record the reference's tally lacks, reference round_votes.rs:
48-56, SURVEY §2.3 fix 2).  A flag alone proves nothing to a third
party; the PROOF is the two conflicting signed votes, which the
ingestion bridges retain (`VoteBatcher._log` / the C++ loop's block
log).  This module is the production join between the two: sweep the
flags, pull each validator's conflicting pair, and emit one record
per (instance, validator) ready for the executor's evidence archive
or a slashing transaction.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Union

import numpy as np

from agnes_tpu.bridge.ingest import VoteBatcher, WireVote
from agnes_tpu.bridge.native_ingest import NativeIngestLoop


@dataclass(frozen=True)
class DeviceEvidence:
    """One device-detected double-sign with its slashable proof."""

    instance: int
    validator: int
    first: WireVote
    second: WireVote


def _wire_from_record(rec: np.ndarray) -> WireVote:
    """Packed 96-byte wire record -> WireVote (the C++ loop's evidence
    format; layout documented at core/native/ingest.cpp top)."""
    b = rec.tobytes()
    value = int.from_bytes(b[24:32], "little")
    return WireVote(
        instance=int.from_bytes(b[0:4], "little"),
        validator=int.from_bytes(b[4:8], "little"),
        height=int.from_bytes(b[8:16], "little", signed=True),
        round=int.from_bytes(b[16:20], "little", signed=True),
        typ=b[20],
        value=value if b[21] & 1 else None,
        signature=b[32:96],
    )


def collect_device_evidence(
    flags, bridge: Union[VoteBatcher, NativeIngestLoop],
) -> List[DeviceEvidence]:
    """Sweep a device equivocation plane and return the signed proofs.

    `flags` is the [I, V] bool plane `DeviceDriver.tally.equiv` (the
    driver's `equivocators_detected()` is its per-instance reduction);
    `bridge` is whichever ingestion bridge fed the device and
    therefore holds the retained verified votes.  Flagged
    pairs whose conflicting votes are no longer in the bridge's log
    (e.g. cleared after a prior extraction) are skipped — the flag
    stays visible in metrics, but there is nothing left to prove with.
    """
    out: List[DeviceEvidence] = []
    f = np.asarray(flags)
    for inst, val in zip(*np.nonzero(f)):
        pair = bridge.signed_evidence(int(inst), int(val))
        if pair is None:
            continue
        a, b = pair
        if isinstance(a, np.ndarray):          # native loop: raw records
            a, b = _wire_from_record(a), _wire_from_record(b)
        if a.signature is None or b.signature is None:
            # votes ingested without signatures (unverified path)
            # conflict but prove nothing to a third party — emitting
            # them as "signed proofs" would ship evidence every
            # checker rejects
            continue
        out.append(DeviceEvidence(int(inst), int(val), a, b))
    return out


def verify_evidence(ev: DeviceEvidence, pubkey: bytes) -> bool:
    """Third-party check of one evidence record: both votes are by the
    same validator for the same (height, round, class) with different
    values, and both signatures verify under `pubkey`."""
    from agnes_tpu.bridge.ingest import vote_messages_np
    from agnes_tpu.crypto import host_verify

    a, b = ev.first, ev.second
    if (a.height, a.round, int(a.typ)) != (b.height, b.round, int(b.typ)):
        return False
    if a.value == b.value or a.signature is None or b.signature is None:
        return False
    for v in (a, b):
        msg = vote_messages_np(
            np.asarray([v.height]), np.asarray([v.round]),
            np.asarray([int(v.typ)]),
            np.asarray([-1 if v.value is None else v.value]))[0]
        if not host_verify(pubkey, msg.tobytes(), v.signature):
            return False
    return True
