"""Domain types for the consensus engine.

Reference parity: src/lib.rs:1-45 defines `Value` (an empty placeholder
struct, lib.rs:3-4), `Proposal {round, value, pol_round}` (lib.rs:9-13),
`VoteType {Prevote, Precommit}` (lib.rs:16-19) and
`Vote {typ, round, value: Option<Value>}` (lib.rs:23-27).

Design decisions for the TPU build (SURVEY.md §2.1):

* **Value is a 31-bit integer id.** The reference's `Value {}` is an empty
  placeholder ("TODO: it should probably be a Trait", lib.rs:2).  On device a
  value must be a fixed-width lane, so the framework agrees on int32 value
  *ids*; arbitrary payloads live in a host-side table keyed by id
  (`bridge.ValueTable`).  `NIL` (python `None` at the API surface, -1 on
  device) is a nil vote — the reference's `Option<Value>::None`.

* **Votes carry identity and signatures.**  The reference deliberately omits
  height, validator address and signature from `Vote` (SURVEY.md §2.1 "notably
  absent") — that surface is exactly what this framework adds: `validator` is
  an index into the ValidatorSet, `signature` a 64-byte Ed25519 signature over
  the canonical vote encoding (`crypto.encoding.vote_signing_bytes`).  Both are
  optional so the pure core remains testable without crypto, preserving the
  reference's decoupling argument (README.md:8-14).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Optional

# Nil vote marker (reference: Option<Value>::None, lib.rs:26).
NIL = None

# Device-side encoding of NIL; value ids must be in [0, 2**31 - 1).
NIL_ID = -1

# Framework-wide rounds domain: [-1, MAX_ROUND], shared by every plane
# (wire screen core/executor.py, int32 device encoding, int64 oracle
# and C++ core).  Round arithmetic SATURATES at MAX_ROUND on all
# planes so they stay bit-for-bit even at the representable edge: a
# round-skip chain parks at MAX_ROUND (and the instance can still
# commit there — PrecommitValue has no round guard, spec line 49)
# instead of wrapping in int32 while widening in int64.
MAX_ROUND = 2**31 - 1


class VoteType(enum.IntEnum):
    """Reference parity: src/lib.rs:16-19."""

    PREVOTE = 0
    PRECOMMIT = 1


@dataclass(frozen=True, slots=True)
class Proposal:
    """A proposed value for a round.

    `pol_round` is -1 or the last round the value got a polka
    (reference: src/lib.rs:6-13).
    """

    round: int
    value: int
    pol_round: int = -1


@dataclass(frozen=True, slots=True)
class Vote:
    """A vote for a value (or nil) in a round.

    Reference parity: src/lib.rs:21-38.  `validator`/`height`/`signature`
    are additions of this framework (see module docstring).
    """

    typ: VoteType
    round: int
    value: Optional[int]  # None = nil vote
    validator: Optional[int] = None
    height: Optional[int] = None
    signature: Optional[bytes] = None

    @classmethod
    def new_prevote(cls, round: int, value: Optional[int], **kw) -> "Vote":
        """Reference parity: Vote::new_prevote, src/lib.rs:30-33."""
        return cls(VoteType.PREVOTE, round, value, **kw)

    @classmethod
    def new_precommit(cls, round: int, value: Optional[int], **kw) -> "Vote":
        """Reference parity: Vote::new_precommit, src/lib.rs:35-38."""
        return cls(VoteType.PRECOMMIT, round, value, **kw)
