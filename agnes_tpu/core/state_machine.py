"""The pure Tendermint consensus state machine.

This module must stay **semantically identical** to the reference
(src/state_machine.rs, 346 LoC) — it is the oracle every other
implementation (JAX device plane, C++ native core) is differentially
tested against.  Commented numbers refer to line numbers in the spec
paper, arXiv 1807.04938, exactly as the reference annotates them
(src/state_machine.rs:182).

The machine is a pure function: `apply(state, round, event) ->
(state', message | None)`.  No I/O, no signatures, no timers — the
consumer resolves proposer-ness, proposal validity and quorum
thresholds into Events before calling apply (reference README.md:36-49);
in this framework that consumer is the TPU data plane
(`agnes_tpu.device`) plus the host driver (`agnes_tpu.core.executor`).

Reference-parity subtleties deliberately preserved (SURVEY.md §2.2):

* the lock/unlock rule on receiving a proposal (state_machine.rs:239-244);
* `PrecommitValue` commits from **any** round — no current-round guard
  (state_machine.rs:211, spec line 49); only Commit step absorbs first;
* `schedule_timeout_prevote`/`_precommit` do NOT advance the step
  (state_machine.rs:287-295);
* `precommit` sets both locked and valid; `set_valid_value` (Precommit
  step) sets only valid and emits nothing (state_machine.rs:261-264,
  304-306);
* `TimeoutPrecommit` moves to round+1, `RoundSkip` jumps to the event's
  (strictly higher) round; both emit `NewRound` (state_machine.rs:314-316);
* proposing reuses the valid value and its round when set, else the
  consumer-supplied value with pol_round -1 (state_machine.rs:222-229);
* `Decision` carries the **event's** round, while the state's round field
  is left untouched by `commit` (state_machine.rs:320-322).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, replace
from typing import Optional, Tuple

from agnes_tpu.types import MAX_ROUND, Proposal, Vote

# ---------------------------------------------------------------------------
# Enums — the integer codes here are THE canonical encoding, shared verbatim
# by the device plane (device/encoding.py) and the C++ core (native/core.h).
# ---------------------------------------------------------------------------


class Step(enum.IntEnum):
    """Step of consensus within a round (reference: state_machine.rs:14-21)."""

    NEW_ROUND = 0
    PROPOSE = 1
    PREVOTE = 2
    PRECOMMIT = 3
    COMMIT = 4


class EventTag(enum.IntEnum):
    """The 13 input events (reference: state_machine.rs:96-110)."""

    NEW_ROUND = 0            # start a new round, not as proposer
    NEW_ROUND_PROPOSER = 1   # start a new round and propose value
    PROPOSAL = 2             # complete proposal received (pol_round, value)
    PROPOSAL_INVALID = 3     # invalid proposal received
    POLKA_ANY = 4            # +2/3 prevotes for anything
    POLKA_NIL = 5            # +2/3 prevotes for nil
    POLKA_VALUE = 6          # +2/3 prevotes for value
    PRECOMMIT_ANY = 7        # +2/3 precommits for anything
    PRECOMMIT_VALUE = 8      # +2/3 precommits for value
    ROUND_SKIP = 9           # +1/3 votes from a higher round
    TIMEOUT_PROPOSE = 10     # timeout waiting for proposal
    TIMEOUT_PREVOTE = 11     # timeout waiting for prevotes
    TIMEOUT_PRECOMMIT = 12   # timeout waiting for precommits


class TimeoutStep(enum.IntEnum):
    """Which step a timeout is for (reference: state_machine.rs:158-163)."""

    PROPOSE = 0
    PREVOTE = 1
    PRECOMMIT = 2


class MsgTag(enum.IntEnum):
    """Output message kinds (reference: state_machine.rs:118-124).

    NONE is this framework's device encoding for Rust's Option::None.
    """

    NONE = 0
    NEW_ROUND = 1
    PROPOSAL = 2
    VOTE = 3
    TIMEOUT = 4
    DECISION = 5


# ---------------------------------------------------------------------------
# Events / Messages
# ---------------------------------------------------------------------------


@dataclass(frozen=True, slots=True)
class Event:
    """A tagged input event; only some tags carry a payload."""

    tag: EventTag
    value: Optional[int] = None    # NEW_ROUND_PROPOSER / PROPOSAL / POLKA_VALUE / PRECOMMIT_VALUE
    pol_round: int = -1            # PROPOSAL only

    # -- constructors (mirror reference event variants) --
    @classmethod
    def new_round(cls):
        return cls(EventTag.NEW_ROUND)

    @classmethod
    def new_round_proposer(cls, value: int):
        return cls(EventTag.NEW_ROUND_PROPOSER, value=value)

    @classmethod
    def proposal(cls, pol_round: int, value: int):
        return cls(EventTag.PROPOSAL, value=value, pol_round=pol_round)

    @classmethod
    def proposal_invalid(cls):
        return cls(EventTag.PROPOSAL_INVALID)

    @classmethod
    def polka_any(cls):
        return cls(EventTag.POLKA_ANY)

    @classmethod
    def polka_nil(cls):
        return cls(EventTag.POLKA_NIL)

    @classmethod
    def polka_value(cls, value: int):
        return cls(EventTag.POLKA_VALUE, value=value)

    @classmethod
    def precommit_any(cls):
        return cls(EventTag.PRECOMMIT_ANY)

    @classmethod
    def precommit_value(cls, value: int):
        return cls(EventTag.PRECOMMIT_VALUE, value=value)

    @classmethod
    def round_skip(cls):
        return cls(EventTag.ROUND_SKIP)

    @classmethod
    def timeout_propose(cls):
        return cls(EventTag.TIMEOUT_PROPOSE)

    @classmethod
    def timeout_prevote(cls):
        return cls(EventTag.TIMEOUT_PREVOTE)

    @classmethod
    def timeout_precommit(cls):
        return cls(EventTag.TIMEOUT_PRECOMMIT)


@dataclass(frozen=True, slots=True)
class Timeout:
    """Reference parity: state_machine.rs:150-155."""

    round: int
    step: TimeoutStep


@dataclass(frozen=True, slots=True)
class RoundValue:
    """A value together with the round it was locked/valid/decided at
    (reference: state_machine.rs:7-11)."""

    round: int
    value: int


@dataclass(frozen=True, slots=True)
class Message:
    """Output of the state machine (reference: state_machine.rs:115-124):
    proposals/votes to sign and broadcast, timeouts to schedule, round
    switches, and the decision."""

    tag: MsgTag
    round: int = 0
    proposal: Optional[Proposal] = None
    vote: Optional[Vote] = None
    timeout: Optional[Timeout] = None
    decision: Optional[RoundValue] = None

    # -- constructors (reference: state_machine.rs:127-148) --
    @classmethod
    def new_round(cls, round: int) -> "Message":
        return cls(MsgTag.NEW_ROUND, round=round)

    @classmethod
    def proposal_msg(cls, round: int, value: int, pol_round: int) -> "Message":
        return cls(MsgTag.PROPOSAL, round=round,
                   proposal=Proposal(round, value, pol_round))

    @classmethod
    def prevote(cls, round: int, value: Optional[int]) -> "Message":
        return cls(MsgTag.VOTE, round=round, vote=Vote.new_prevote(round, value))

    @classmethod
    def precommit(cls, round: int, value: Optional[int]) -> "Message":
        return cls(MsgTag.VOTE, round=round, vote=Vote.new_precommit(round, value))

    @classmethod
    def timeout_msg(cls, round: int, step: TimeoutStep) -> "Message":
        return cls(MsgTag.TIMEOUT, round=round, timeout=Timeout(round, step))

    @classmethod
    def decision_msg(cls, round: int, value: int) -> "Message":
        return cls(MsgTag.DECISION, round=round,
                   decision=RoundValue(round, value))


# ---------------------------------------------------------------------------
# State
# ---------------------------------------------------------------------------


@dataclass(frozen=True, slots=True)
class State:
    """Consensus state for one (height) instance
    (reference: state_machine.rs:23-31).

    Immutable: every transition returns a fresh State — the purity
    invariant the TPU data plane relies on (functional array updates).
    Height never changes; a decision ends the instance and the driver
    starts a new State at the next height (reference README.md:43-44).
    """

    height: int
    round: int = 0
    step: Step = Step.NEW_ROUND
    locked: Optional[RoundValue] = None
    valid: Optional[RoundValue] = None

    @classmethod
    def new(cls, height: int) -> "State":
        """Fresh state at round 0, NewRound (state_machine.rs:35-43)."""
        return cls(height=height)

    # -- pure helpers (reference: state_machine.rs:46-89) --

    def set_round(self, round: int) -> "State":
        """Back to NewRound at `round` (state_machine.rs:46-52)."""
        return replace(self, round=round, step=Step.NEW_ROUND)

    def next_step(self) -> "State":
        """NewRound→Propose→Prevote→Precommit, saturating
        (state_machine.rs:58-66)."""
        nxt = {
            Step.NEW_ROUND: Step.PROPOSE,
            Step.PROPOSE: Step.PREVOTE,
            Step.PREVOTE: Step.PRECOMMIT,
        }.get(self.step, self.step)
        return replace(self, step=nxt)

    def commit_step(self) -> "State":
        """Terminal Commit step (state_machine.rs:70-75)."""
        return replace(self, step=Step.COMMIT)

    def set_locked(self, value: int) -> "State":
        """Lock `value` at the current round (state_machine.rs:78-82)."""
        return replace(self, locked=RoundValue(self.round, value))

    def set_valid(self, value: int) -> "State":
        """Record `value` as valid at the current round
        (state_machine.rs:85-89)."""
        return replace(self, valid=RoundValue(self.round, value))

    def valid_vr(self, vr: int) -> bool:
        """Is `vr` a plausible pol_round for this round?
        (state_machine.rs:170-172)."""
        return -1 <= vr < self.round

    def apply(self, round: int, event: Event) -> Tuple["State", Optional[Message]]:
        return apply(self, round, event)


# ---------------------------------------------------------------------------
# Transition function
# ---------------------------------------------------------------------------


def apply(s: State, round: int, event: Event) -> Tuple[State, Optional[Message]]:
    """Transition the machine: returns (new state, output message or None).

    `round` is the round the event belongs to; most transitions require it
    to equal the state's current round (`eqr`, reference
    state_machine.rs:184).  The arm order below matters and matches the
    reference match expression (state_machine.rs:185-213) exactly —
    in particular Commit-step absorption comes before the step-agnostic
    arms, and `PRECOMMIT_VALUE` carries no round guard.
    """
    eqr = s.round == round
    step, tag = s.step, event.tag
    E = EventTag

    # From NewRound. Event must be for current round. (state_machine.rs:186-188)
    if step == Step.NEW_ROUND and tag == E.NEW_ROUND_PROPOSER and eqr:
        return _propose(s, event.value)                      # 11/14
    if step == Step.NEW_ROUND and tag == E.NEW_ROUND and eqr:
        return _schedule_timeout_propose(s)                  # 11/20

    # From Propose. Event must be for current round. (state_machine.rs:190-193)
    if step == Step.PROPOSE and tag == E.PROPOSAL and eqr and s.valid_vr(event.pol_round):
        return _prevote(s, event.pol_round, event.value)     # 22, 28
    if step == Step.PROPOSE and tag == E.PROPOSAL_INVALID and eqr:
        return _prevote_nil(s)                               # 22/25, 28/31
    if step == Step.PROPOSE and tag == E.TIMEOUT_PROPOSE and eqr:
        return _prevote_nil(s)                               # 57

    # From Prevote. Event must be for current round. (state_machine.rs:195-199)
    if step == Step.PREVOTE and tag == E.POLKA_ANY and eqr:
        return _schedule_timeout_prevote(s)                  # 34
    if step == Step.PREVOTE and tag == E.POLKA_NIL and eqr:
        return _precommit_nil(s)                             # 44
    if step == Step.PREVOTE and tag == E.POLKA_VALUE and eqr:
        return _precommit(s, event.value)                    # 36/37
    if step == Step.PREVOTE and tag == E.TIMEOUT_PREVOTE and eqr:
        return _precommit_nil(s)                             # 61

    # From Precommit. Event must be for current round. (state_machine.rs:201-202)
    if step == Step.PRECOMMIT and tag == E.POLKA_VALUE and eqr:
        return _set_valid_value(s, event.value)              # 36/42

    # From Commit. No more state transitions. (state_machine.rs:204-205)
    if step == Step.COMMIT:
        return s, None

    # From all other steps. Various round guards. (state_machine.rs:207-211)
    if tag == E.PRECOMMIT_ANY and eqr:
        return _schedule_timeout_precommit(s)                # 47
    if tag == E.TIMEOUT_PRECOMMIT and eqr:
        # the framework rounds domain is [-1, MAX_ROUND] (types.py):
        # saturate the skip target there so the int64 oracle/C++ and
        # the int32 device plane stay bit-for-bit at the edge — a
        # screened-in round of MAX_ROUND must not widen to 2**31 here
        # while wrapping negative on device
        return _round_skip(s, min(round + 1, MAX_ROUND))     # 65
    if tag == E.ROUND_SKIP and s.round < round:
        return _round_skip(s, round)                         # 55
    if tag == E.PRECOMMIT_VALUE:                             # no round guard!
        return _commit(s, round, event.value)                # 49

    return s, None


# -- transition actions (reference: state_machine.rs:216-322) --


def _propose(s: State, v: int) -> Tuple[State, Optional[Message]]:
    """We are the proposer: propose the valid value if one exists, else `v`
    (state_machine.rs:222-229, spec 11/14)."""
    s = s.next_step()
    if s.valid is not None:
        value, pol_round = s.valid.value, s.valid.round
    else:
        value, pol_round = v, -1
    return s, Message.proposal_msg(s.round, value, pol_round)


def _prevote(s: State, vr: int, proposed: int) -> Tuple[State, Optional[Message]]:
    """Complete proposal received: prevote it unless locked on a different
    value at a round > vr (state_machine.rs:237-246, spec 22, 28)."""
    s = s.next_step()
    if s.locked is None:
        value = proposed                      # not locked, prevote the value
    elif s.locked.round <= vr:
        value = proposed                      # unlock and prevote
    elif s.locked.value == proposed:
        value = proposed                      # already locked on this value
    else:
        value = None                          # locked on other value: nil
    return s, Message.prevote(s.round, value)


def _prevote_nil(s: State) -> Tuple[State, Optional[Message]]:
    """Invalid proposal or propose timeout (state_machine.rs:250-253)."""
    s = s.next_step()
    return s, Message.prevote(s.round, None)


def _precommit(s: State, v: int) -> Tuple[State, Optional[Message]]:
    """Polka for a value: lock it, mark valid, precommit it
    (state_machine.rs:261-264, spec 36)."""
    s = s.set_locked(v).set_valid(v).next_step()
    return s, Message.precommit(s.round, v)


def _precommit_nil(s: State) -> Tuple[State, Optional[Message]]:
    """Polka for nil or prevote timeout (state_machine.rs:268-271, spec 44/61)."""
    s = s.next_step()
    return s, Message.precommit(s.round, None)


def _schedule_timeout_propose(s: State) -> Tuple[State, Optional[Message]]:
    """Not the proposer: wait for a proposal (state_machine.rs:278-281)."""
    s = s.next_step()
    return s, Message.timeout_msg(s.round, TimeoutStep.PROPOSE)


def _schedule_timeout_prevote(s: State) -> Tuple[State, Optional[Message]]:
    """Polka for any: schedule prevote timeout; the step does NOT advance
    (state_machine.rs:287-289, spec 34)."""
    return s, Message.timeout_msg(s.round, TimeoutStep.PREVOTE)


def _schedule_timeout_precommit(s: State) -> Tuple[State, Optional[Message]]:
    """+2/3 precommits for any: schedule precommit timeout; no step change
    (state_machine.rs:293-295, spec 47)."""
    return s, Message.timeout_msg(s.round, TimeoutStep.PRECOMMIT)


def _set_valid_value(s: State, v: int) -> Tuple[State, Optional[Message]]:
    """Polka after we already precommitted: record valid, emit nothing
    (state_machine.rs:304-306, spec 36/42)."""
    return s.set_valid(v), None


def _round_skip(s: State, r: int) -> Tuple[State, Optional[Message]]:
    """Precommit timeout or +1/3 from a higher round: move to round `r`
    (state_machine.rs:314-316, spec 65/55)."""
    return s.set_round(r), Message.new_round(r)


def _commit(s: State, r: int, v: int) -> Tuple[State, Optional[Message]]:
    """+2/3 precommits for a value: decide it.  Note the state's round field
    is untouched and the Decision carries the event's round
    (state_machine.rs:320-322, spec 49)."""
    return s.commit_step(), Message.decision_msg(r, v)
