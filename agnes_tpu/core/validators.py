"""Validator set: public keys, voting power, proposer rotation.

Reference parity: src/validators.rs (56 LoC) — which does not compile as
shipped (SURVEY.md §2.6) — defines the *intent* implemented here:
a validator is a (public key, voting power) pair (validators.rs:5-8), a
validator's address is derived from its public key (validators.rs:15-17),
and a ValidatorSet is an address-sorted, deduplicated, mutable collection
(validators.rs:23-56) with a hash (validators.rs:11-13, TODO there).

Framework additions beyond the reference's intent:

* **Proposer rotation** — the "check if we're the proposer" stub at
  consensus_executor.rs:31-33 needs a deterministic proposer per
  (height, round).  `ProposerRotation` implements the classic Tendermint
  weighted round-robin: every step each validator's priority increases by
  its power, the max-priority validator proposes and pays the total power.
  Over time each validator proposes proportionally to its power.
  `proposer_table` precomputes a [heights, rounds] proposer-index table for
  upload to the device plane.

* **Device export** — `device_arrays()` yields the device-resident tables
  of the north star (BASELINE.json): [n, 32] uint8 Ed25519 public keys and
  [n] int64 voting powers, address-sorted so device index == host index.
"""

from __future__ import annotations

import bisect
import hashlib
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

PUBKEY_LEN = 32  # Ed25519 compressed public key


@dataclass(frozen=True, slots=True)
class Validator:
    """A public key and voting power (reference: validators.rs:4-8)."""

    public_key: bytes  # 32-byte Ed25519 public key
    voting_power: int

    def __post_init__(self):
        if len(self.public_key) != PUBKEY_LEN:
            raise ValueError(
                f"public_key must be {PUBKEY_LEN} bytes, got {len(self.public_key)}")
        if self.voting_power < 0:
            raise ValueError("voting_power must be non-negative")

    @property
    def address(self) -> bytes:
        """The validator's address: its public key (validators.rs:15-17
        returns the key directly; real Tendermint truncates a hash — we
        keep the reference's simpler rule)."""
        return self.public_key

    def hash(self) -> bytes:
        """Canonical digest of (key, power) — fills validators.rs:11-13's
        TODO with sha256 over a fixed-width encoding."""
        return hashlib.sha256(
            self.public_key + self.voting_power.to_bytes(8, "big")).digest()


class ValidatorSet:
    """Address-sorted, deduplicated validator collection
    (reference: validators.rs:22-56, intent)."""

    def __init__(self, validators: Iterable[Validator] = ()):
        # bulk path: dedup by address (latest wins), one sort — O(n log n)
        latest: Dict[bytes, Validator] = {v.address: v for v in validators}
        self._validators: List[Validator] = sorted(
            latest.values(), key=lambda v: v.address)
        self._by_address: Dict[bytes, int] = {}
        self._reindex()

    # -- internal ----------------------------------------------------------

    def _insert(self, val: Validator) -> None:
        """Insert keeping address order; an existing address is replaced
        (dedup, validators.rs:54)."""
        existing = self._by_address.get(val.address)
        if existing is not None:
            self._validators[existing] = val
            return
        i = bisect.bisect_left([v.address for v in self._validators], val.address)
        self._validators.insert(i, val)
        self._reindex()

    def _reindex(self) -> None:
        self._by_address = {v.address: i for i, v in enumerate(self._validators)}

    # -- queries -----------------------------------------------------------

    def __len__(self) -> int:
        return len(self._validators)

    def __iter__(self):
        return iter(self._validators)

    def __getitem__(self, i: int) -> Validator:
        return self._validators[i]

    def index_of(self, address: bytes) -> Optional[int]:
        return self._by_address.get(address)

    @property
    def total_power(self) -> int:
        return sum(v.voting_power for v in self._validators)

    def hash(self) -> bytes:
        """Digest of the whole set (order-sensitive)."""
        h = hashlib.sha256()
        for v in self._validators:
            h.update(v.hash())
        return h.digest()

    # -- mutation (reference: validators.rs:33-46) -------------------------

    def add(self, val: Validator) -> None:
        self._insert(val)

    def update(self, val: Validator) -> None:
        """Update the voting power of an existing validator
        (validators.rs:38-41, empty TODO body there)."""
        i = self._by_address.get(val.address)
        if i is None:
            raise KeyError("unknown validator")
        self._validators[i] = val

    def remove(self, address: bytes) -> None:
        """Remove by address (validators.rs:43-46, empty TODO body)."""
        i = self._by_address.get(address)
        if i is None:
            raise KeyError("unknown validator")
        del self._validators[i]
        self._reindex()

    # -- device export -----------------------------------------------------

    def device_arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        """(pubkeys [n, 32] uint8, powers [n] int64), address-sorted —
        the device-resident validator table (BASELINE.json north star)."""
        n = len(self._validators)
        keys = np.zeros((n, PUBKEY_LEN), dtype=np.uint8)
        powers = np.zeros((n,), dtype=np.int64)
        for i, v in enumerate(self._validators):
            keys[i] = np.frombuffer(v.public_key, dtype=np.uint8)
            powers[i] = v.voting_power
        return keys, powers


@dataclass
class ProposerRotation:
    """Tendermint-style weighted round-robin proposer selection.

    Fills the "check if we're the proposer" stub (consensus_executor.rs:
    31-33).  Stateful: call `step()` once per (height, round) in order.
    Deterministic given the validator set, so every node computes the same
    proposer sequence.
    """

    vset: ValidatorSet
    # priorities are keyed by address so the rotation survives validator-set
    # changes: newcomers start at priority 0, removed validators drop out.
    priorities: Dict[bytes, int] = field(default_factory=dict)

    def step(self) -> int:
        """Advance one proposer slot; returns the proposer's index in the
        current (address-sorted) set."""
        if len(self.vset) == 0:
            raise ValueError("empty validator set")
        total = self.vset.total_power
        addrs = [v.address for v in self.vset]
        self.priorities = {a: self.priorities.get(a, 0) for a in addrs}
        for v in self.vset:
            self.priorities[v.address] += v.voting_power
        # max priority wins; ties break toward the lower address (index)
        proposer = max(range(len(addrs)),
                       key=lambda i: (self.priorities[addrs[i]], -i))
        self.priorities[addrs[proposer]] -= total
        return proposer


def proposer_table(vset: ValidatorSet, n_heights: int, n_rounds: int,
                   start_height: int = 0,
                   rotation: Optional[ProposerRotation] = None) -> np.ndarray:
    """Precompute proposer indices for a [n_heights, n_rounds] window —
    uploaded to the device so 10k vmapped instances can resolve
    NewRound vs NewRoundProposer without host round-trips.

    The rotation is a single global sequence walked in (height, round)
    order starting from genesis.  For sliding windows pass the `rotation`
    carried over from the previous call (it is advanced in place) instead
    of `start_height`, which replays start_height*n_rounds steps from
    genesis and is only meant for small offsets/tests."""
    rot = rotation if rotation is not None else ProposerRotation(vset)
    if rotation is None:
        for _ in range(start_height * n_rounds):
            rot.step()
    table = np.zeros((n_heights, n_rounds), dtype=np.int32)
    for h in range(n_heights):
        for r in range(n_rounds):
            table[h, r] = rot.step()
    return table
