"""Pure consensus core (host oracle) + native C++ runtime bindings."""
