"""Vote tally for one round: weights, quorum thresholds, equivocation.

Reference parity: src/round_votes.rs (133 LoC).  The quorum predicate,
threshold priority order, and the Any-threshold definition are kept
exactly:

* `is_quorum(v, total) = 3*v > 2*total` — strictly more than 2/3 of the
  *fixed total* voting power, not of votes seen (round_votes.rs:31-33,
  total fixed at construction :36-44);
* threshold priority Value > Nil > Any > Init (round_votes.rs:58-66);
* `Any` is quorum of **all** weight seen, value + nil buckets together
  (round_votes.rs:62).

Two documented limitations of the reference are fixed here, not copied
(SURVEY.md §2.3 "known limitations to fix"):

1. **Per-value buckets.**  The reference accumulates all non-nil weight
   into a single bucket, conflating distinct values (round_votes.rs:50-54,
   TODOs :14, :51).  Here each distinct value id gets its own bucket; the
   reported Value threshold is for the highest-weight value that actually
   has a quorum.

2. **Per-validator deduplication / equivocation detection.**  The
   reference double-counts a re-sent vote (round_votes.rs:48-56; its own
   test at :120-122 exercises this).  Here, when votes carry a validator
   index, a validator's weight counts at most once per (round, vote type):
   a duplicate of the same vote is ignored, and a *conflicting* vote for a
   different value is recorded as equivocation evidence (the double-sign /
   slashing surface, BASELINE config 5) — the first vote keeps counting.
   Votes without a validator index (the pure-core test path, matching the
   reference's identity-free Vote, lib.rs:23-27) are never deduplicated,
   preserving reference behavior exactly.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from agnes_tpu.types import Vote, VoteType


class ThreshKind(enum.IntEnum):
    INIT = 0   # no quorum
    ANY = 1    # quorum of votes, but not for one value
    NIL = 2    # quorum for nil
    VALUE = 3  # quorum for a specific value


@dataclass(frozen=True, slots=True)
class Thresh:
    """Quorum threshold reached by a vote class
    (reference: round_votes.rs:21-28)."""

    kind: ThreshKind
    value: Optional[int] = None

    @classmethod
    def init(cls) -> "Thresh":
        return cls(ThreshKind.INIT)

    @classmethod
    def any(cls) -> "Thresh":
        return cls(ThreshKind.ANY)

    @classmethod
    def nil(cls) -> "Thresh":
        return cls(ThreshKind.NIL)

    @classmethod
    def for_value(cls, v: int) -> "Thresh":
        return cls(ThreshKind.VALUE, v)


def is_quorum(value: int, total: int) -> bool:
    """True iff value > (2/3) * total (reference: round_votes.rs:31-33)."""
    return 3 * value > 2 * total


def is_one_third(value: int, total: int) -> bool:
    """True iff value > (1/3) * total — the RoundSkip trigger
    ("+1/3 votes from a higher round", reference state_machine.rs:106)."""
    return 3 * value > total


@dataclass(frozen=True, slots=True)
class Equivocation:
    """Double-sign evidence: one validator, two conflicting votes of the
    same type in the same round.  No reference analogue (the reference has
    no validator identity); this is BASELINE config 5's slashing surface."""

    height: int
    round: int
    typ: VoteType
    validator: int
    first_value: Optional[int]
    second_value: Optional[int]


@dataclass
class VoteCount:
    """Tally of one vote class (prevotes or precommits) for one round.

    Reference parity: round_votes.rs:12-67, with per-value buckets
    (fix 1 above).  `total` is the total voting power of the validator
    set, fixed at construction.
    """

    total: int
    nil: int = 0
    weights: Dict[int, int] = field(default_factory=dict)  # value id -> weight

    def add(self, value: Optional[int], weight: int) -> Thresh:
        """Accumulate `weight` for `value` (None = nil) and return the
        highest threshold now reached, priority Value > Nil > Any > Init
        (reference: round_votes.rs:48-67)."""
        if value is None:
            self.nil += weight
        else:
            self.weights[value] = self.weights.get(value, 0) + weight
        return self.thresh()

    def value_weight(self, value: Optional[int]) -> int:
        if value is None:
            return self.nil
        return self.weights.get(value, 0)

    def seen_weight(self) -> int:
        """Total weight seen across all buckets (nil included)."""
        return self.nil + sum(self.weights.values())

    def quorum_value(self) -> Optional[int]:
        """The highest-weight value with a quorum, if any.  At most one
        value can have >2/3, so the tie-break (highest weight, then
        smallest value id) only matters in adversarial >total-weight
        streams (identity-free votes); it is deterministic and mirrored
        by the C++ core's ascending-id map iteration."""
        best = None
        best_w = -1
        for v, w in self.weights.items():
            if is_quorum(w, self.total) and (
                    w > best_w or (w == best_w and v < best)):
                best, best_w = v, w
        return best

    def thresh(self) -> Thresh:
        qv = self.quorum_value()
        if qv is not None:
            return Thresh.for_value(qv)
        if is_quorum(self.nil, self.total):
            return Thresh.nil()
        if is_quorum(self.seen_weight(), self.total):
            return Thresh.any()
        return Thresh.init()

    def clone(self) -> "VoteCount":
        """Shallow-bucket copy (state-space branching surface)."""
        return VoteCount(self.total, self.nil, dict(self.weights))


@dataclass
class RoundVotes:
    """All votes for a single (height, round): a prevote tally, a precommit
    tally, and the per-validator dedup/equivocation record
    (reference: round_votes.rs:73-98 + SURVEY.md §2.3 fix 2)."""

    height: int
    round: int
    total: int
    prevotes: VoteCount = None  # type: ignore[assignment]
    precommits: VoteCount = None  # type: ignore[assignment]
    # (validator, typ) -> (value, weight) of their first (counted) vote
    seen: Dict[Tuple[int, VoteType], Tuple[Optional[int], int]] = field(default_factory=dict)
    equivocations: List[Equivocation] = field(default_factory=list)
    # (validator, typ) pairs already flagged — one evidence record per pair
    _flagged: set = field(default_factory=set)
    # weight from identity-free votes, per vote type (reference-parity path)
    _anon_weight: Dict[VoteType, int] = field(default_factory=dict)

    def __post_init__(self):
        if self.prevotes is None:
            self.prevotes = VoteCount(self.total)
        if self.precommits is None:
            self.precommits = VoteCount(self.total)

    def _count(self, typ: VoteType) -> VoteCount:
        return self.prevotes if typ == VoteType.PREVOTE else self.precommits

    def add_vote(self, vote: Vote, weight: int) -> Thresh:
        """Add a vote; returns the highest threshold of that vote's class
        (reference: round_votes.rs:92-97).  Dedup/equivocation only when
        the vote carries a validator index (see module docstring)."""
        count = self._count(vote.typ)
        if vote.validator is not None:
            key = (vote.validator, vote.typ)
            if key in self.seen:
                prior, _w = self.seen[key]
                if prior != vote.value and key not in self._flagged:
                    # one evidence record per (validator, type); redeliveries
                    # of the conflicting vote don't grow the list
                    self._flagged.add(key)
                    self.equivocations.append(Equivocation(
                        self.height, self.round, vote.typ, vote.validator,
                        prior, vote.value))
                return count.thresh()  # duplicate or conflict: not counted
            self.seen[key] = (vote.value, weight)
        else:
            self._anon_weight[vote.typ] = self._anon_weight.get(vote.typ, 0) + weight
        return count.add(vote.value, weight)

    def clone(self) -> "RoundVotes":
        """One-level copy: every container is duplicated, the leaves
        (Vote values, Equivocation records) are frozen and shared —
        the state-space branching surface (analysis/modelcheck.py)."""
        rv = RoundVotes(self.height, self.round, self.total,
                        prevotes=self.prevotes.clone(),
                        precommits=self.precommits.clone(),
                        seen=dict(self.seen),
                        equivocations=list(self.equivocations),
                        _flagged=set(self._flagged),
                        _anon_weight=dict(self._anon_weight))
        return rv

    def skip_weight(self) -> int:
        """Weight of distinct voters seen in this round — the +1/3
        RoundSkip trigger on rounds above the current one (reference
        state_machine.rs:106 names the event; detection is absent there).
        With validator identity each voter counts once regardless of vote
        type; identity-free weight contributes the larger single class so a
        both-types voter is not double-counted.  Mixed streams combine
        both contributions."""
        by_validator: Dict[int, int] = {}
        for (v, _t), (_val, w) in self.seen.items():
            by_validator[v] = max(by_validator.get(v, 0), w)
        anon = max(self._anon_weight.get(VoteType.PREVOTE, 0),
                   self._anon_weight.get(VoteType.PRECOMMIT, 0))
        return sum(by_validator.values()) + anon
