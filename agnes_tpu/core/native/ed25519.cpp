// Ed25519 implementation (RFC 8032).  See ed25519.hpp.
//
// Field: GF(2^255-19) in radix-2^51 (5 uint64 limbs, __int128
// products).  Curve constants (d, sqrt(-1), the base point) are
// *derived at startup* from their definitions rather than embedded as
// magic tables; only the group order L — spec data — is written out.
// Oracle for tests: agnes_tpu/crypto/ed25519_ref.py + RFC vectors.

#include "ed25519.hpp"

#include <cstring>

#include "sha512.hpp"

namespace agnes {
namespace {

using u128 = unsigned __int128;

constexpr uint64_t kMask51 = (1ULL << 51) - 1;

// --- field ------------------------------------------------------------------

struct Fe {
  uint64_t v[5];
};

const Fe kFeZero = {{0, 0, 0, 0, 0}};
const Fe kFeOne = {{1, 0, 0, 0, 0}};

void fe_carry(Fe* f) {
  for (int pass = 0; pass < 2; ++pass) {
    for (int i = 0; i < 4; ++i) {
      f->v[i + 1] += f->v[i] >> 51;
      f->v[i] &= kMask51;
    }
    uint64_t c = f->v[4] >> 51;
    f->v[4] &= kMask51;
    f->v[0] += 19 * c;   // 2^255 === 19
  }
}

Fe fe_add(const Fe& a, const Fe& b) {
  Fe r;
  for (int i = 0; i < 5; ++i) r.v[i] = a.v[i] + b.v[i];
  fe_carry(&r);
  return r;
}

Fe fe_sub(const Fe& a, const Fe& b) {
  // a + 4p - b keeps every limb positive (limbs < 2^52 < 4p_i)
  Fe r;
  r.v[0] = a.v[0] + ((1ULL << 53) - 76) - b.v[0];
  for (int i = 1; i < 5; ++i)
    r.v[i] = a.v[i] + ((1ULL << 53) - 4) - b.v[i];
  fe_carry(&r);
  return r;
}

Fe fe_mul(const Fe& a, const Fe& b) {
  const uint64_t a0 = a.v[0], a1 = a.v[1], a2 = a.v[2], a3 = a.v[3],
                 a4 = a.v[4];
  const uint64_t b0 = b.v[0], b1 = b.v[1], b2 = b.v[2], b3 = b.v[3],
                 b4 = b.v[4];
  const uint64_t t1 = 19 * b1, t2 = 19 * b2, t3 = 19 * b3, t4 = 19 * b4;
  u128 r0 = (u128)a0 * b0 + (u128)a1 * t4 + (u128)a2 * t3 + (u128)a3 * t2 +
            (u128)a4 * t1;
  u128 r1 = (u128)a0 * b1 + (u128)a1 * b0 + (u128)a2 * t4 + (u128)a3 * t3 +
            (u128)a4 * t2;
  u128 r2 = (u128)a0 * b2 + (u128)a1 * b1 + (u128)a2 * b0 + (u128)a3 * t4 +
            (u128)a4 * t3;
  u128 r3 = (u128)a0 * b3 + (u128)a1 * b2 + (u128)a2 * b1 + (u128)a3 * b0 +
            (u128)a4 * t4;
  u128 r4 = (u128)a0 * b4 + (u128)a1 * b3 + (u128)a2 * b2 + (u128)a3 * b1 +
            (u128)a4 * b0;
  Fe out;
  u128 c;
  c = r0 >> 51; r0 &= kMask51; r1 += c;
  c = r1 >> 51; r1 &= kMask51; r2 += c;
  c = r2 >> 51; r2 &= kMask51; r3 += c;
  c = r3 >> 51; r3 &= kMask51; r4 += c;
  c = r4 >> 51; r4 &= kMask51; r0 += 19 * c;
  c = r0 >> 51; r0 &= kMask51; r1 += c;
  out.v[0] = (uint64_t)r0; out.v[1] = (uint64_t)r1; out.v[2] = (uint64_t)r2;
  out.v[3] = (uint64_t)r3; out.v[4] = (uint64_t)r4;
  return out;
}

Fe fe_sqr(const Fe& a) { return fe_mul(a, a); }

// exponent as 256-bit little-endian words; variable time (public data)
Fe fe_pow(const Fe& a, const uint64_t e[4]) {
  Fe r = kFeOne;
  for (int i = 255; i >= 0; --i) {
    r = fe_sqr(r);
    if ((e[i / 64] >> (i % 64)) & 1) r = fe_mul(r, a);
  }
  return r;
}

const uint64_t kPm2[4] = {0xFFFFFFFFFFFFFFEBULL, 0xFFFFFFFFFFFFFFFFULL,
                          0xFFFFFFFFFFFFFFFFULL,
                          0x7FFFFFFFFFFFFFFFULL};  // p - 2
const uint64_t kPm5d8[4] = {0xFFFFFFFFFFFFFFFDULL, 0xFFFFFFFFFFFFFFFFULL,
                            0xFFFFFFFFFFFFFFFFULL,
                            0x0FFFFFFFFFFFFFFFULL};  // (p - 5) / 8

Fe fe_invert(const Fe& a) { return fe_pow(a, kPm2); }

void fe_tobytes(const Fe& f, uint8_t out[32]) {
  Fe t = f;
  fe_carry(&t);
  fe_carry(&t);
  // value < 2^255 + eps; at most one conditional subtract of p
  uint64_t p0 = kMask51 - 18;  // 2^51 - 19
  bool ge = t.v[0] >= p0;
  for (int i = 1; i < 5; ++i) ge = ge && (t.v[i] == kMask51);
  if (ge) {
    t.v[0] -= p0;
    for (int i = 1; i < 5; ++i) t.v[i] = 0;
  }
  std::memset(out, 0, 32);
  for (int i = 0; i < 5; ++i) {
    int bit = 51 * i;
    for (int b = 0; b < 8; ++b) {   // (v << 7) spans up to 8 bytes
      int pos = bit / 8 + b;
      if (pos < 32) out[pos] |= (uint8_t)((t.v[i] << (bit % 8)) >> (8 * b));
    }
  }
}

void fe_frombytes(const uint8_t in[32], Fe* f) {
  for (int i = 0; i < 5; ++i) {
    int bit = 51 * i;
    uint64_t v = 0;
    for (int b = 7; b >= 0; --b) {
      int pos = bit / 8 + b;
      if (pos < 32) v = (v << 8) | in[pos];
    }
    f->v[i] = (v >> (bit % 8)) & kMask51;
  }
  // bit 255 (the sign bit) sits above limb 4's 51-bit mask: dropped.
}

bool fe_eq(const Fe& a, const Fe& b) {
  uint8_t ba[32], bb[32];
  fe_tobytes(a, ba);
  fe_tobytes(b, bb);
  return std::memcmp(ba, bb, 32) == 0;
}

bool fe_iszero(const Fe& a) { return fe_eq(a, kFeZero); }

Fe fe_from_u64(uint64_t x) {
  Fe f = kFeZero;
  f.v[0] = x & kMask51;
  f.v[1] = x >> 51;
  return f;
}

// --- derived curve constants ------------------------------------------------

struct Consts {
  Fe d, d2, sqrt_m1;
  Fe bx, by, bt;   // base point affine + x*y
  Consts();
};

// group point
struct Ge {
  Fe x, y, z, t;
};

Ge ge_identity() { return {kFeZero, kFeOne, kFeOne, kFeZero}; }

const Consts& C();

Ge ge_add(const Ge& p, const Ge& q) {
  // unified a=-1 twisted Edwards addition (complete)
  Fe a = fe_mul(fe_sub(p.y, p.x), fe_sub(q.y, q.x));
  Fe b = fe_mul(fe_add(p.y, p.x), fe_add(q.y, q.x));
  Fe c = fe_mul(fe_mul(p.t, q.t), C().d2);
  Fe zz = fe_mul(p.z, q.z);
  Fe d = fe_add(zz, zz);
  Fe e = fe_sub(b, a), f = fe_sub(d, c), g = fe_add(d, c), h = fe_add(b, a);
  return {fe_mul(e, f), fe_mul(g, h), fe_mul(f, g), fe_mul(e, h)};
}

Ge ge_neg(const Ge& p) {
  return {fe_sub(kFeZero, p.x), p.y, p.z, fe_sub(kFeZero, p.t)};
}

// variable-time scalar mult, scalar as 256-bit LE words
Ge ge_scalar_mul(const uint64_t s[4], const Ge& p) {
  Ge r = ge_identity();
  for (int i = 255; i >= 0; --i) {
    r = ge_add(r, r);
    if ((s[i / 64] >> (i % 64)) & 1) r = ge_add(r, p);
  }
  return r;
}

bool ge_decompress(const uint8_t in[32], Ge* out) {
  uint8_t sign = in[31] >> 7;
  Fe y;
  fe_frombytes(in, &y);
  // reject non-canonical y (>= p)
  uint8_t canon[32];
  fe_tobytes(y, canon);
  uint8_t raw[32];
  std::memcpy(raw, in, 32);
  raw[31] &= 0x7F;
  if (std::memcmp(canon, raw, 32) != 0) return false;

  Fe y2 = fe_sqr(y);
  Fe u = fe_sub(y2, kFeOne);
  Fe v = fe_add(fe_mul(y2, C().d), kFeOne);
  Fe v3 = fe_mul(v, fe_sqr(v));
  Fe v7 = fe_mul(v3, fe_mul(v3, v));
  Fe x = fe_mul(fe_mul(u, v3), fe_pow(fe_mul(u, v7), kPm5d8));
  Fe vx2 = fe_mul(v, fe_sqr(x));
  if (fe_eq(vx2, u)) {
    // ok
  } else if (fe_eq(vx2, fe_sub(kFeZero, u))) {
    x = fe_mul(x, C().sqrt_m1);
  } else {
    return false;
  }
  uint8_t xb[32];
  fe_tobytes(x, xb);
  if (fe_iszero(x) && sign) return false;
  if ((xb[0] & 1) != sign) x = fe_sub(kFeZero, x);
  *out = {x, y, kFeOne, fe_mul(x, y)};
  return true;
}

void ge_compress(const Ge& p, uint8_t out[32]) {
  Fe zi = fe_invert(p.z);
  Fe x = fe_mul(p.x, zi);
  Fe y = fe_mul(p.y, zi);
  uint8_t xb[32];
  fe_tobytes(x, xb);
  fe_tobytes(y, out);
  out[31] |= (xb[0] & 1) << 7;
}

Consts::Consts() {
  // all derived from definitions; must not call anything that re-enters
  // C() (the magic-static is still under construction here)
  Fe n121665 = fe_sub(kFeZero, fe_from_u64(121665));
  d = fe_mul(n121665, fe_invert(fe_from_u64(121666)));  // -121665/121666
  d2 = fe_add(d, d);
  // sqrt(-1) = 2^((p-1)/4); (p-1)/4 = (2^255-20)/4 = 2^253 - 5
  const uint64_t e_quarter[4] = {0xFFFFFFFFFFFFFFFBULL,
                                 0xFFFFFFFFFFFFFFFFULL,
                                 0xFFFFFFFFFFFFFFFFULL,
                                 0x1FFFFFFFFFFFFFFFULL};
  sqrt_m1 = fe_pow(fe_from_u64(2), e_quarter);
  // base point: y = 4/5, x recovered with sign 0 (inline x-recovery —
  // ge_decompress would re-enter C())
  by = fe_mul(fe_from_u64(4), fe_invert(fe_from_u64(5)));
  Fe y2 = fe_sqr(by);
  Fe u = fe_sub(y2, kFeOne);
  Fe v = fe_add(fe_mul(y2, d), kFeOne);
  Fe v3 = fe_mul(v, fe_sqr(v));
  Fe v7 = fe_mul(v3, fe_mul(v3, v));
  Fe x = fe_mul(fe_mul(u, v3), fe_pow(fe_mul(u, v7), kPm5d8));
  if (!fe_eq(fe_mul(v, fe_sqr(x)), u)) x = fe_mul(x, sqrt_m1);
  uint8_t xb[32];
  fe_tobytes(x, xb);
  if (xb[0] & 1) x = fe_sub(kFeZero, x);   // canonical sign 0
  bx = x;
  bt = fe_mul(bx, by);
}

const Consts& C() {
  static Consts c;
  return c;
}

Ge ge_base() { return {C().bx, C().by, kFeOne, C().bt}; }

// --- scalars mod L ----------------------------------------------------------

struct U256 {
  uint64_t w[4];
};

const U256 kL = {{0x5812631A5CF5D3EDULL, 0x14DEF9DEA2F79CD6ULL, 0,
                  0x1000000000000000ULL}};  // RFC 8032 group order

bool u256_geq(const U256& a, const U256& b) {
  for (int i = 3; i >= 0; --i) {
    if (a.w[i] != b.w[i]) return a.w[i] > b.w[i];
  }
  return true;
}

void u256_sub(U256* a, const U256& b) {
  u128 borrow = 0;
  for (int i = 0; i < 4; ++i) {
    u128 t = (u128)a->w[i] - b.w[i] - borrow;
    a->w[i] = (uint64_t)t;
    borrow = (t >> 64) & 1;
  }
}

// r = x mod L for a bit-addressable big-endian-scanned value
U256 mod_l_bits(const uint8_t* le_bytes, int n_bytes) {
  U256 r = {{0, 0, 0, 0}};
  for (int i = 8 * n_bytes - 1; i >= 0; --i) {
    // r <<= 1 (r < L < 2^253, shift is safe)
    for (int j = 3; j > 0; --j)
      r.w[j] = (r.w[j] << 1) | (r.w[j - 1] >> 63);
    r.w[0] <<= 1;
    r.w[0] |= (le_bytes[i / 8] >> (i % 8)) & 1;
    if (u256_geq(r, kL)) u256_sub(&r, kL);
  }
  return r;
}

U256 u256_frombytes(const uint8_t in[32]) {
  U256 r;
  for (int i = 0; i < 4; ++i) {
    r.w[i] = 0;
    for (int b = 7; b >= 0; --b) r.w[i] = (r.w[i] << 8) | in[8 * i + b];
  }
  return r;
}

void u256_tobytes(const U256& a, uint8_t out[32]) {
  for (int i = 0; i < 4; ++i)
    for (int b = 0; b < 8; ++b) out[8 * i + b] = (a.w[i] >> (8 * b)) & 0xFF;
}

U256 mulmod_l(const U256& a, const U256& b) {
  uint64_t prod[8] = {0};
  for (int i = 0; i < 4; ++i) {
    u128 carry = 0;
    for (int j = 0; j < 4; ++j) {
      u128 t = (u128)a.w[i] * b.w[j] + prod[i + j] + carry;
      prod[i + j] = (uint64_t)t;
      carry = t >> 64;
    }
    prod[i + 4] = (uint64_t)carry;
  }
  uint8_t bytes[64];
  for (int i = 0; i < 8; ++i)
    for (int b = 0; b < 8; ++b)
      bytes[8 * i + b] = (prod[i] >> (8 * b)) & 0xFF;
  return mod_l_bits(bytes, 64);
}

U256 addmod_l(const U256& a, const U256& b) {
  U256 r;
  u128 carry = 0;
  for (int i = 0; i < 4; ++i) {
    u128 t = (u128)a.w[i] + b.w[i] + carry;
    r.w[i] = (uint64_t)t;
    carry = t >> 64;
  }
  // a, b < L < 2^253: no word overflow; single conditional subtract
  if (u256_geq(r, kL)) u256_sub(&r, kL);
  return r;
}

void clamp(uint8_t h[32]) {
  h[0] &= 248;
  h[31] &= 127;
  h[31] |= 64;
}

}  // namespace

// --- public API -------------------------------------------------------------

void ed25519_pubkey(const uint8_t seed[32], uint8_t out_pk[32]) {
  uint8_t h[64];
  sha512(seed, 32, h);
  clamp(h);
  U256 a = u256_frombytes(h);
  ge_compress(ge_scalar_mul(a.w, ge_base()), out_pk);
}

void ed25519_sign(const uint8_t seed[32], const uint8_t* msg, uint64_t n,
                  uint8_t out_sig[64]) {
  uint8_t h[64];
  sha512(seed, 32, h);
  clamp(h);
  U256 a = u256_frombytes(h);
  uint8_t pk[32];
  ge_compress(ge_scalar_mul(a.w, ge_base()), pk);

  Sha512 hr;
  hr.update(h + 32, 32);
  hr.update(msg, n);
  uint8_t rh[64];
  hr.final(rh);
  U256 r = mod_l_bits(rh, 64);
  ge_compress(ge_scalar_mul(r.w, ge_base()), out_sig);  // R

  Sha512 hk;
  hk.update(out_sig, 32);
  hk.update(pk, 32);
  hk.update(msg, n);
  uint8_t kh[64];
  hk.final(kh);
  U256 k = mod_l_bits(kh, 64);
  U256 s = addmod_l(r, mulmod_l(k, a));
  u256_tobytes(s, out_sig + 32);
}

bool ed25519_verify(const uint8_t pk[32], const uint8_t* msg, uint64_t n,
                    const uint8_t sig[64]) {
  Ge a;
  if (!ge_decompress(pk, &a)) return false;
  U256 s = u256_frombytes(sig + 32);
  if (u256_geq(s, kL)) return false;  // S < L (RFC 8032 §5.1.7)

  Sha512 hk;
  hk.update(sig, 32);
  hk.update(pk, 32);
  hk.update(msg, n);
  uint8_t kh[64];
  hk.final(kh);
  U256 k = mod_l_bits(kh, 64);

  // COFACTORED check (the framework-wide policy; see
  // crypto/ed25519_ref.py verify): R must decode canonically, then
  // [8]([S]B + [k](-A)) == [8]R — multiply-by-8 makes single, batch
  // (MSM) and per-lane verification agree on every input, so vote
  // validity is a pure function of the signature bytes.
  Ge r;
  if (!ge_decompress(sig, &r)) return false;
  Ge q = ge_add(ge_scalar_mul(s.w, ge_base()),
                ge_scalar_mul(k.w, ge_neg(a)));
  for (int i = 0; i < 3; ++i) {
    q = ge_add(q, q);
    r = ge_add(r, r);
  }
  uint8_t qb[32], rb[32];
  ge_compress(q, qb);
  ge_compress(r, rb);
  return std::memcmp(qb, rb, 32) == 0;
}

}  // namespace agnes
