// SHA-512 implementation (FIPS 180-4).  K/IV constants come from the
// build-time generated sha512_k.inc (see sha512.hpp).

#include "sha512.hpp"

#include <cstring>

namespace agnes {

namespace {
#include "sha512_k.inc"   // defines kK[80] and kH0[8]

inline uint64_t rotr(uint64_t x, int n) { return (x >> n) | (x << (64 - n)); }
inline uint64_t big_sigma0(uint64_t a) {
  return rotr(a, 28) ^ rotr(a, 34) ^ rotr(a, 39);
}
inline uint64_t big_sigma1(uint64_t e) {
  return rotr(e, 14) ^ rotr(e, 18) ^ rotr(e, 41);
}
inline uint64_t sm_sigma0(uint64_t w) {
  return rotr(w, 1) ^ rotr(w, 8) ^ (w >> 7);
}
inline uint64_t sm_sigma1(uint64_t w) {
  return rotr(w, 19) ^ rotr(w, 61) ^ (w >> 6);
}

void compress(uint64_t h[8], const uint8_t block[128]) {
  uint64_t w[80];
  for (int t = 0; t < 16; ++t) {
    w[t] = 0;
    for (int b = 0; b < 8; ++b)
      w[t] = (w[t] << 8) | block[8 * t + b];
  }
  for (int t = 16; t < 80; ++t)
    w[t] = sm_sigma1(w[t - 2]) + w[t - 7] + sm_sigma0(w[t - 15]) + w[t - 16];

  uint64_t a = h[0], b = h[1], c = h[2], d = h[3];
  uint64_t e = h[4], f = h[5], g = h[6], hh = h[7];
  for (int t = 0; t < 80; ++t) {
    uint64_t t1 = hh + big_sigma1(e) + ((e & f) ^ (~e & g)) + kK[t] + w[t];
    uint64_t t2 = big_sigma0(a) + ((a & b) ^ (a & c) ^ (b & c));
    hh = g; g = f; f = e; e = d + t1;
    d = c; c = b; b = a; a = t1 + t2;
  }
  h[0] += a; h[1] += b; h[2] += c; h[3] += d;
  h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
}

// --- SHA-256 (FIPS 180-4 §6.2) ---------------------------------------------

inline uint32_t rotr32(uint32_t x, int n) {
  return (x >> n) | (x << (32 - n));
}

void compress256(uint32_t h[8], const uint8_t block[64]) {
  uint32_t w[64];
  for (int t = 0; t < 16; ++t)
    w[t] = (uint32_t(block[4 * t]) << 24) |
           (uint32_t(block[4 * t + 1]) << 16) |
           (uint32_t(block[4 * t + 2]) << 8) | uint32_t(block[4 * t + 3]);
  for (int t = 16; t < 64; ++t) {
    uint32_t s0 = rotr32(w[t - 15], 7) ^ rotr32(w[t - 15], 18) ^
                  (w[t - 15] >> 3);
    uint32_t s1 = rotr32(w[t - 2], 17) ^ rotr32(w[t - 2], 19) ^
                  (w[t - 2] >> 10);
    w[t] = w[t - 16] + s0 + w[t - 7] + s1;
  }
  uint32_t a = h[0], b = h[1], c = h[2], d = h[3];
  uint32_t e = h[4], f = h[5], g = h[6], hh = h[7];
  for (int t = 0; t < 64; ++t) {
    uint32_t t1 = hh + (rotr32(e, 6) ^ rotr32(e, 11) ^ rotr32(e, 25)) +
                  ((e & f) ^ (~e & g)) + kK256[t] + w[t];
    uint32_t t2 = (rotr32(a, 2) ^ rotr32(a, 13) ^ rotr32(a, 22)) +
                  ((a & b) ^ (a & c) ^ (b & c));
    hh = g; g = f; f = e; e = d + t1;
    d = c; c = b; b = a; a = t1 + t2;
  }
  h[0] += a; h[1] += b; h[2] += c; h[3] += d;
  h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
}

}  // namespace

void sha256(const uint8_t* data, size_t n, uint8_t out[32]) {
  uint32_t h[8];
  std::memcpy(h, kH256, sizeof(h));
  size_t off = 0;
  for (; off + 64 <= n; off += 64) compress256(h, data + off);
  // final: remainder + 0x80 pad + zero fill + 64-bit bit length —
  // at most two trailing blocks (rem <= 63, so rem + 1 + 8 <= 128)
  uint8_t buf[128];
  size_t rem = n - off;
  if (rem) std::memcpy(buf, data + off, rem);
  buf[rem++] = 0x80;
  size_t blocks = (rem + 8 <= 64) ? 1 : 2;
  std::memset(buf + rem, 0, blocks * 64 - 8 - rem);
  uint64_t bits = uint64_t(n) << 3;
  for (int i = 0; i < 8; ++i)
    buf[blocks * 64 - 8 + i] = (bits >> (56 - 8 * i)) & 0xFF;
  compress256(h, buf);
  if (blocks == 2) compress256(h, buf + 64);
  for (int i = 0; i < 8; ++i)
    for (int b = 0; b < 4; ++b)
      out[4 * i + b] = (h[i] >> (24 - 8 * b)) & 0xFF;
}

Sha512::Sha512() { std::memcpy(h, kH0, sizeof(h)); }

void Sha512::update(const uint8_t* data, size_t n) {
  size_t fill = static_cast<size_t>(len % 128);
  len += n;
  if (fill) {
    size_t take = 128 - fill;
    if (take > n) take = n;
    std::memcpy(buf + fill, data, take);
    data += take; n -= take; fill += take;
    if (fill == 128) compress(h, buf);
    else return;
  }
  while (n >= 128) {
    compress(h, data);
    data += 128; n -= 128;
  }
  if (n) std::memcpy(buf, data, n);
}

void Sha512::final(uint8_t out[64]) {
  uint64_t bits_hi = len >> 61, bits_lo = len << 3;
  size_t fill = static_cast<size_t>(len % 128);
  buf[fill++] = 0x80;
  if (fill > 112) {
    std::memset(buf + fill, 0, 128 - fill);
    compress(h, buf);
    fill = 0;
  }
  std::memset(buf + fill, 0, 112 - fill);
  for (int i = 0; i < 8; ++i) buf[112 + i] = (bits_hi >> (56 - 8 * i)) & 0xFF;
  for (int i = 0; i < 8; ++i) buf[120 + i] = (bits_lo >> (56 - 8 * i)) & 0xFF;
  compress(h, buf);
  for (int i = 0; i < 8; ++i)
    for (int b = 0; b < 8; ++b)
      out[8 * i + b] = (h[i] >> (56 - 8 * b)) & 0xFF;
}

void sha512(const uint8_t* data, size_t n, uint8_t out[64]) {
  Sha512 s;
  s.update(data, n);
  s.final(out);
}

}  // namespace agnes
