// Flat C ABI over the native core for the ctypes bindings
// (agnes_tpu/core/native.py).  POD structs mirror the Python dataclass
// encodings field-for-field; handles are opaque pointers.

#include <cstdint>
#include <cstring>
#include <vector>

#include "core.hpp"
#include "ed25519.hpp"
#include "sha512.hpp"

extern "C" {

struct AgState {
  int64_t height, round;
  int32_t step;
  int32_t has_locked, has_valid;
  int64_t locked_round, locked_value, valid_round, valid_value;
};

struct AgEvent {
  int32_t tag;
  int32_t has_value;
  int64_t value;
  int64_t pol_round;
};

struct AgMessage {
  int32_t tag;
  int64_t round;
  int64_t p_value, p_pol_round;
  int32_t v_typ, v_has_value;
  int64_t v_value;
  int32_t t_step;
  int64_t d_round, d_value;
};

static void to_cpp(const AgState& in, agnes::State* out) {
  out->height = in.height;
  out->round = in.round;
  out->step = static_cast<agnes::Step>(in.step);
  out->has_locked = in.has_locked != 0;
  out->has_valid = in.has_valid != 0;
  out->locked_round = in.locked_round;
  out->locked_value = in.locked_value;
  out->valid_round = in.valid_round;
  out->valid_value = in.valid_value;
}

static void from_cpp(const agnes::State& in, AgState* out) {
  out->height = in.height;
  out->round = in.round;
  out->step = static_cast<int32_t>(in.step);
  out->has_locked = in.has_locked ? 1 : 0;
  out->has_valid = in.has_valid ? 1 : 0;
  out->locked_round = in.locked_round;
  out->locked_value = in.locked_value;
  out->valid_round = in.valid_round;
  out->valid_value = in.valid_value;
}

void ag_apply(const AgState* s, int64_t round, const AgEvent* e,
              AgState* out_s, AgMessage* out_m) {
  agnes::State st;
  to_cpp(*s, &st);
  agnes::Event ev;
  ev.tag = static_cast<agnes::EventTag>(e->tag);
  ev.has_value = e->has_value != 0;
  ev.value = e->value;
  ev.pol_round = e->pol_round;
  agnes::State ns;
  agnes::Message msg;
  agnes::apply(st, round, ev, &ns, &msg);
  from_cpp(ns, out_s);
  std::memset(out_m, 0, sizeof(*out_m));
  out_m->tag = static_cast<int32_t>(msg.tag);
  out_m->round = msg.round;
  out_m->p_value = msg.p_value;
  out_m->p_pol_round = msg.p_pol_round;
  out_m->v_typ = static_cast<int32_t>(msg.v_typ);
  out_m->v_has_value = msg.v_has_value ? 1 : 0;
  out_m->v_value = msg.v_value;
  out_m->t_step = static_cast<int32_t>(msg.t_step);
  out_m->d_round = msg.d_round;
  out_m->d_value = msg.d_value;
}

// --- tally handle -----------------------------------------------------------

void* ag_tally_new(int64_t height, int64_t round, int64_t total) {
  // hostile negative totals would make is_quorum(0, total) true (an
  // empty tally reporting a quorum); clamp to the empty-set total here
  // so the core keeps exact Python-oracle parity for in-domain inputs
  if (total < 0) total = 0;
  return new agnes::RoundVotes(height, round, total);
}

void ag_tally_free(void* t) {
  delete static_cast<agnes::RoundVotes*>(t);
}

// returns ThreshKind; *thresh_value = value for kind Value, else -1.
// validator/value use -1 as None.
int32_t ag_tally_add(void* t, int32_t typ, int64_t validator, int64_t value,
                     int64_t weight, int64_t* thresh_value) {
  auto* rv = static_cast<agnes::RoundVotes*>(t);
  return static_cast<int32_t>(
      rv->add_vote(static_cast<agnes::VoteType>(typ), validator, value,
                   weight, thresh_value));
}

int64_t ag_tally_skip_weight(void* t) {
  return static_cast<agnes::RoundVotes*>(t)->skip_weight();
}

int64_t ag_tally_equiv_count(void* t) {
  return static_cast<int64_t>(
      static_cast<agnes::RoundVotes*>(t)->equivocations().size());
}

// each evidence row: [round, typ, validator, first_value, second_value];
// returns count written (<= cap)
int64_t ag_tally_equivocations(void* t, int64_t* out, int64_t cap) {
  const auto& eq = static_cast<agnes::RoundVotes*>(t)->equivocations();
  int64_t n = 0;
  for (const auto& e : eq) {
    if (n >= cap) break;
    out[5 * n + 0] = e.round;
    out[5 * n + 1] = static_cast<int64_t>(e.typ);
    out[5 * n + 2] = e.validator;
    out[5 * n + 3] = e.first_value;
    out[5 * n + 4] = e.second_value;
    ++n;
  }
  return n;
}

// --- validator set ----------------------------------------------------------

// vals: n rows of (32 pubkey bytes, int64 power) packed as 40-byte rows
void* ag_valset_new(const uint8_t* packed, int64_t n) {
  std::vector<agnes::Validator> vals(static_cast<size_t>(n));
  for (int64_t i = 0; i < n; ++i) {
    std::memcpy(vals[i].public_key, packed + 40 * i, 32);
    int64_t p = 0;
    std::memcpy(&p, packed + 40 * i + 32, 8);
    vals[i].voting_power = p;
  }
  return new agnes::ValidatorSet(std::move(vals));
}

void ag_valset_free(void* v) {
  delete static_cast<agnes::ValidatorSet*>(v);
}

int64_t ag_valset_len(void* v) {
  return static_cast<int64_t>(
      static_cast<agnes::ValidatorSet*>(v)->validators().size());
}

int64_t ag_valset_total_power(void* v) {
  return static_cast<agnes::ValidatorSet*>(v)->total_power();
}

int64_t ag_valset_index_of(void* v, const uint8_t* pk) {
  return static_cast<agnes::ValidatorSet*>(v)->index_of(pk);
}

void* ag_rotation_new(void* valset) {
  return new agnes::ProposerRotation(
      static_cast<agnes::ValidatorSet*>(valset));
}

void ag_rotation_free(void* r) {
  delete static_cast<agnes::ProposerRotation*>(r);
}

int64_t ag_rotation_step(void* r) {
  return static_cast<agnes::ProposerRotation*>(r)->step();
}

void ag_valset_hash(void* v, uint8_t* out32) {
  static_cast<agnes::ValidatorSet*>(v)->hash(out32);
}

// row i of out: (pubkey 32B, power int64) — sorted order
void ag_valset_get(void* v, uint8_t* packed_out) {
  const auto& vals = static_cast<agnes::ValidatorSet*>(v)->validators();
  for (size_t i = 0; i < vals.size(); ++i) {
    std::memcpy(packed_out + 40 * i, vals[i].public_key, 32);
    std::memcpy(packed_out + 40 * i + 32, &vals[i].voting_power, 8);
  }
}

int32_t ag_valset_update(void* v, const uint8_t* pk, int64_t power) {
  agnes::Validator val;
  std::memcpy(val.public_key, pk, 32);
  val.voting_power = power;
  return static_cast<agnes::ValidatorSet*>(v)->update(val) ? 1 : 0;
}

void ag_valset_add(void* v, const uint8_t* pk, int64_t power) {
  agnes::Validator val;
  std::memcpy(val.public_key, pk, 32);
  val.voting_power = power;
  static_cast<agnes::ValidatorSet*>(v)->add(val);
}

int32_t ag_valset_remove(void* v, const uint8_t* pk) {
  return static_cast<agnes::ValidatorSet*>(v)->remove(pk) ? 1 : 0;
}

// --- crypto -----------------------------------------------------------------

void ag_sha512(const uint8_t* data, int64_t n, uint8_t* out64) {
  agnes::sha512(data, static_cast<size_t>(n), out64);
}

void ag_ed25519_pubkey(const uint8_t* seed, uint8_t* out_pk) {
  agnes::ed25519_pubkey(seed, out_pk);
}

void ag_ed25519_sign(const uint8_t* seed, const uint8_t* msg, int64_t n,
                     uint8_t* out_sig) {
  agnes::ed25519_sign(seed, msg, static_cast<uint64_t>(n), out_sig);
}

int32_t ag_ed25519_verify(const uint8_t* pk, const uint8_t* msg, int64_t n,
                          const uint8_t* sig) {
  return agnes::ed25519_verify(pk, msg, static_cast<uint64_t>(n), sig) ? 1
                                                                       : 0;
}

// batch verify: fixed-length messages, contiguous arrays
void ag_ed25519_verify_batch(const uint8_t* pks, const uint8_t* sigs,
                             const uint8_t* msgs, int64_t msg_len,
                             int64_t count, uint8_t* out_ok) {
  for (int64_t i = 0; i < count; ++i) {
    out_ok[i] = agnes::ed25519_verify(
                    pks + 32 * i, msgs + msg_len * i,
                    static_cast<uint64_t>(msg_len), sigs + 64 * i)
                    ? 1
                    : 0;
  }
}

}  // extern "C"
