// Sharded native ingest (ISSUE 20 tentpole, layer 2): N admission
// shards per host — one AdmQ (and one mutex) per shard, instance-range
// partitioned exactly like distributed/topology.HostPlan (shard s owns
// instances [s*L, (s+1)*L), L = I / n_shards) — behind ONE submit
// fan-in that routes each 96-byte record by its instance id.  Two
// producer threads landing on different shards never touch the same
// mutex; the PR 14 single-queue design funneled the whole host through
// one.
//
// Correctness anchors:
//
//   - Fairness caps and overload policies are PER-SHARD-CORRECT
//     because the partition key IS the fairness key: an instance's
//     occupancy and rank-within-submit live entirely in its owning
//     shard, so per-instance caps are exact at any shard count.
//     Capacity is split evenly (capacity / n_shards per shard), so
//     *aggregate* overflow behavior near the capacity ceiling differs
//     from a single queue when the instance mix is skewed — the
//     wrapper documents this and the conformance grid keeps its
//     byte-identity schedules below the per-shard ceiling.
//
//   - The drain is a deterministic K-WAY MERGE: all shard mutexes are
//     taken in ascending shard order (a fixed hierarchy — lockcheck's
//     LOCK001 ordering argument), then the globally-oldest record by
//     (seq, sub_idx) is popped repeatedly.  Every shard deque is
//     sorted by (seq, sub_idx) (see admission.hpp), so the merged
//     stream replays the single-queue admission order byte-for-byte
//     whenever the accept decisions agree.
//
//   - Digests come back in GLOBAL admission order: submit_records
//     reports a per-position kept mask, and the fan-in walks the
//     original record order gathering each shard's compact digests.
//     The (seq -> per-admitted-record shard) route is remembered so
//     mark_verified can split the cache's global hit mask back into
//     per-shard masks; routes are stored only when digests are on
//     (the wrapper always marks when a cache is attached) and are
//     dropped once consumed.
//
// The C ABI mirrors ag_adm_* one-for-one under the ag_adms_ prefix so
// the audited ctypes wrapper stays a thin twin.

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <limits>
#include <mutex>
#include <unordered_map>
#include <vector>

#include "admission.hpp"

namespace {

using namespace agnes_adm;

struct AdmShards {
  int64_t n_shards, I, L;
  bool digests;
  std::vector<AdmQ*> shards;
  std::atomic<int64_t> next_seq{0};
  // seq -> shard id per ADMITTED record (global admission order);
  // consumed by mark_verified.  Bounded defensively: a wrapper that
  // breaks the always-mark contract must not leak the host.
  std::mutex route_mu;
  std::unordered_map<int64_t, std::vector<int32_t>> routes;
};

constexpr size_t kRouteCapSafety = 65536;

inline int64_t shard_of(const AdmShards* G, int64_t inst) {
  // out-of-range instances ride to shard 0, whose range screen counts
  // them malformed — the single queue's taxonomy, one home
  if (inst < 0 || inst >= G->I) return 0;
  return inst / G->L;
}

// pop the globally-oldest n records across all shards by (seq,
// sub_idx); all shard mutexes held in ascending order for the whole
// merge so the stream is a consistent snapshot.  Each shard's drained
// counter is charged its own popped count.
void merge_pop(AdmShards* G, int64_t& n, std::vector<NRec>& rows) {
  std::vector<std::unique_lock<std::mutex>> locks;
  locks.reserve(static_cast<size_t>(G->n_shards));
  for (AdmQ* A : G->shards) locks.emplace_back(A->mu);
  int64_t total = 0;
  for (AdmQ* A : G->shards)
    total += static_cast<int64_t>(A->q.size());
  if (n < 0) n = 0;
  if (n > total) n = total;
  rows.reserve(static_cast<size_t>(n));
  for (int64_t k = 0; k < n; ++k) {
    AdmQ* best = nullptr;
    for (AdmQ* A : G->shards) {
      if (A->q.empty()) continue;
      const NRec& f = A->q.front();
      if (!best || f.seq < best->q.front().seq ||
          (f.seq == best->q.front().seq &&
           f.sub_idx < best->q.front().sub_idx))
        best = A;
    }
    rows.push_back(best->q.front());
    pop_front(best, 1);
    best->counters[6]++;
  }
}

}  // namespace

extern "C" {

void* ag_adms_new(int64_t n_shards, int64_t I, int64_t capacity,
                  int64_t instance_cap, int32_t policy,
                  int32_t with_digests) {
  // shard-count and divisibility screens fail closed like ag_adm_new;
  // I % n_shards == 0 is the HostPlan contract (equal instance
  // ranges), capacity % n_shards == 0 keeps the per-shard ceiling an
  // integer the wrapper can report exactly
  if (n_shards <= 0 || n_shards > 256 || I <= 0 ||
      I % n_shards != 0 || capacity <= 0 ||
      capacity % n_shards != 0 || capacity / n_shards <= 0 ||
      instance_cap <= 0 || (policy != 0 && policy != 1))
    return nullptr;
  try {
    auto* G = new AdmShards();
    G->n_shards = n_shards;
    G->I = I;
    G->L = I / n_shards;
    G->digests = with_digests != 0;
    G->shards.reserve(static_cast<size_t>(n_shards));
    for (int64_t s = 0; s < n_shards; ++s) {
      auto* A = new AdmQ();
      A->I = I;   // global instance ids; routing enforces the range
      A->capacity = capacity / n_shards;
      A->instance_cap = instance_cap;
      A->policy = policy;
      A->digests = with_digests != 0;
      A->inst_counts.assign(static_cast<size_t>(I), 0);
      A->seen.assign(static_cast<size_t>(I), 0);
      A->seen_epoch.assign(static_cast<size_t>(I), 0);
      G->shards.push_back(A);
    }
    return G;
  } catch (...) {
    return nullptr;
  }
}

void ag_adms_free(void* h) {
  auto* G = static_cast<AdmShards*>(h);
  if (!G) return;
  for (AdmQ* A : G->shards) delete A;
  delete G;
}

int64_t ag_adms_n_shards(void* h) {
  return static_cast<AdmShards*>(h)->n_shards;
}

// the submit fan-in: route each whole record to its owning shard,
// run the exact per-shard screens (no mutex shared between shards —
// producers on disjoint ranges never contend), then gather digests
// back into global admission order.  out_counts/out_digests have the
// ag_adm_submit layout.  Returns the group seq.
int64_t ag_adms_submit(void* h, const uint8_t* buf, int64_t nbytes,
                       int64_t* out_counts, uint8_t* out_digests) {
  auto* G = static_cast<AdmShards*>(h);
  const int64_t n_whole = nbytes / kRecSize;
  const int64_t tail = (nbytes % kRecSize) ? 1 : 0;
  const int64_t seq = G->next_seq.fetch_add(1) + 1;

  // partition by owning shard, preserving original record order
  std::vector<std::vector<int64_t>> ridx(
      static_cast<size_t>(G->n_shards));
  std::vector<int32_t> home(static_cast<size_t>(n_whole));
  for (int64_t k = 0; k < n_whole; ++k) {
    const int64_t s = shard_of(G, rec_instance(buf + k * kRecSize));
    home[static_cast<size_t>(k)] = static_cast<int32_t>(s);
    ridx[static_cast<size_t>(s)].push_back(k);
  }

  int64_t counts[5] = {0, 0, 0, 0, 0};
  std::vector<std::vector<uint8_t>> digs(
      static_cast<size_t>(G->n_shards));
  std::vector<std::vector<uint8_t>> kept(
      static_cast<size_t>(G->n_shards));
  for (int64_t s = 0; s < G->n_shards; ++s) {
    auto& idx = ridx[static_cast<size_t>(s)];
    const int64_t tail_s = (s == 0) ? tail : 0;
    if (idx.empty() && tail_s == 0) continue;
    int64_t c5[5];
    auto& dg = digs[static_cast<size_t>(s)];
    auto& kp = kept[static_cast<size_t>(s)];
    if (G->digests && out_digests) dg.resize(idx.size() * 32);
    kp.resize(idx.size());
    submit_records(G->shards[static_cast<size_t>(s)], buf, idx.data(),
                   static_cast<int64_t>(idx.size()), tail_s, seq, c5,
                   dg.empty() ? nullptr : dg.data(),
                   kp.empty() ? nullptr : kp.data());
    for (int j = 0; j < 5; ++j) counts[j] += c5[j];
  }
  for (int j = 0; j < 5; ++j) out_counts[j] = counts[j];

  // gather digests into GLOBAL admission order + remember the route
  if (G->digests && counts[0] > 0) {
    std::vector<int32_t> route;
    route.reserve(static_cast<size_t>(counts[0]));
    std::vector<int64_t> cur(static_cast<size_t>(G->n_shards), 0);
    std::vector<int64_t> adm(static_cast<size_t>(G->n_shards), 0);
    int64_t out = 0;
    for (int64_t k = 0; k < n_whole; ++k) {
      const size_t s = static_cast<size_t>(home[static_cast<size_t>(k)]);
      const int64_t pos = cur[s]++;
      if (!kept[s][static_cast<size_t>(pos)]) continue;
      if (out_digests)
        std::memcpy(out_digests + 32 * out,
                    digs[s].data() + 32 * adm[s], 32);
      adm[s]++;
      ++out;
      route.push_back(static_cast<int32_t>(s));
    }
    std::lock_guard<std::mutex> g(G->route_mu);
    if (G->routes.size() >= kRouteCapSafety) G->routes.clear();
    G->routes[seq] = std::move(route);
  }
  return seq;
}

void ag_adms_set_chunk_ts(void* h, int64_t seq, double ts) {
  auto* G = static_cast<AdmShards*>(h);
  for (AdmQ* A : G->shards) set_chunk_ts_core(A, seq, ts);
}

// split the cache's global hit mask back per shard using the remembered
// route, then run each shard's exact back-walk.  The wrapper calls this
// for EVERY accepted digest-bearing submit (hits or not) so the route
// entry is always consumed.
void ag_adms_mark_verified(void* h, int64_t seq, const uint8_t* ver,
                           int64_t n) {
  auto* G = static_cast<AdmShards*>(h);
  std::vector<int32_t> route;
  {
    std::lock_guard<std::mutex> g(G->route_mu);
    auto it = G->routes.find(seq);
    if (it == G->routes.end()) return;
    route = std::move(it->second);
    G->routes.erase(it);
  }
  if (n > static_cast<int64_t>(route.size()))
    n = static_cast<int64_t>(route.size());
  std::vector<std::vector<uint8_t>> per(
      static_cast<size_t>(G->n_shards));
  for (int64_t j = 0; j < n; ++j)
    per[static_cast<size_t>(route[static_cast<size_t>(j)])].push_back(
        ver[j]);
  for (int64_t s = 0; s < G->n_shards; ++s) {
    auto& m = per[static_cast<size_t>(s)];
    if (!m.empty())
      mark_verified_core(G->shards[static_cast<size_t>(s)], seq,
                         m.data(), static_cast<int64_t>(m.size()));
  }
}

int64_t ag_adms_depth(void* h) {
  auto* G = static_cast<AdmShards*>(h);
  int64_t d = 0;
  for (AdmQ* A : G->shards) {
    std::lock_guard<std::mutex> g(A->mu);
    d += static_cast<int64_t>(A->q.size());
  }
  return d;
}

int64_t ag_adms_shard_depth(void* h, int64_t s) {
  auto* G = static_cast<AdmShards*>(h);
  if (s < 0 || s >= G->n_shards) return 0;
  AdmQ* A = G->shards[static_cast<size_t>(s)];
  std::lock_guard<std::mutex> g(A->mu);
  return static_cast<int64_t>(A->q.size());
}

int64_t ag_adms_instance_depth(void* h, int64_t i) {
  auto* G = static_cast<AdmShards*>(h);
  if (i < 0 || i >= G->I) return 0;
  AdmQ* A = G->shards[static_cast<size_t>(shard_of(G, i))];
  std::lock_guard<std::mutex> g(A->mu);
  return A->inst_counts[static_cast<size_t>(i)];
}

// guarded min over every shard's stamped heads (the ISSUE 20
// oldest_ts fix, grouped): NaN only when nothing stamped anywhere
double ag_adms_oldest_ts(void* h) {
  auto* G = static_cast<AdmShards*>(h);
  double best = std::numeric_limits<double>::quiet_NaN();
  for (AdmQ* A : G->shards) {
    const double t = min_stamped_ts(A);
    if (!std::isnan(t) && (std::isnan(best) || t < best)) best = t;
  }
  return best;
}

void ag_adms_counters(void* h, int64_t* out7) {
  auto* G = static_cast<AdmShards*>(h);
  for (int j = 0; j < 7; ++j) out7[j] = 0;
  for (AdmQ* A : G->shards) {
    std::lock_guard<std::mutex> g(A->mu);
    for (int j = 0; j < 7; ++j) out7[j] += A->counters[j];
  }
}

void ag_adms_shard_counters(void* h, int64_t s, int64_t* out7) {
  auto* G = static_cast<AdmShards*>(h);
  for (int j = 0; j < 7; ++j) out7[j] = 0;
  if (s < 0 || s >= G->n_shards) return;
  AdmQ* A = G->shards[static_cast<size_t>(s)];
  std::lock_guard<std::mutex> g(A->mu);
  for (int j = 0; j < 7; ++j) out7[j] = A->counters[j];
}

// foreign-outcome fold (the BLS class-table path) charges shard 0 —
// the aggregate taxonomy is what the drain report sums
void ag_adms_add_counters(void* h, const int64_t* deltas5) {
  auto* G = static_cast<AdmShards*>(h);
  AdmQ* A = G->shards[0];
  std::lock_guard<std::mutex> g(A->mu);
  for (int k = 0; k < 5; ++k) A->counters[k] += deltas5[k];
}

// k-way merged drain: the ag_adm_drain twin over the shard group
int64_t ag_adms_drain(void* h, int64_t n, int64_t* inst, int64_t* val,
                      int64_t* hts, int64_t* rnd, int64_t* typ,
                      int64_t* value, uint8_t* sigs, uint8_t* ver,
                      uint8_t* out_dig, double* ts) {
  auto* G = static_cast<AdmShards*>(h);
  std::vector<NRec> rows;
  merge_pop(G, n, rows);
  for (int64_t k = 0; k < n; ++k)
    parse_record(rows[static_cast<size_t>(k)], k, inst, val, hts, rnd,
                 typ, value, sigs, ver, out_dig, ts);
  return n;
}

// merged drain + zero-copy densify: merge-pop the rows, then run the
// same densify as the single queue's phase drain (same eligibility,
// same bail-to-Python contract).  Signature mirrors
// ag_adm_drain_phases.
int64_t ag_adms_drain_phases(
    void* h, int64_t n, int64_t* inst, int64_t* val, int64_t* hts,
    int64_t* rnd, int64_t* typ, int64_t* value, uint8_t* sigs,
    uint8_t* ver, uint8_t* out_dig, double* ts,
    const int64_t* win_heights, const int64_t* win_base, int64_t W,
    const int64_t* slot_lut, int64_t S, int64_t V,
    const uint8_t* pubkeys, int64_t lane_floor, int64_t max_votes,
    int64_t phase_offset, int64_t pad_cap, int32_t* ph_slots,
    uint8_t* ph_mask, int64_t* ph_typ, int64_t* ph_counts,
    int32_t* ln_pub, int32_t* ln_sig, uint32_t* ln_blocks,
    int32_t* ln_phase_idx, int32_t* ln_inst, int32_t* ln_val,
    uint8_t* ln_real, int64_t* ln_rows, int64_t* out_meta) {
  auto* G = static_cast<AdmShards*>(h);
  std::vector<NRec> rows;
  merge_pop(G, n, rows);
  for (int64_t k = 0; k < n; ++k)
    parse_record(rows[static_cast<size_t>(k)], k, inst, val, hts, rnd,
                 typ, value, sigs, ver, out_dig, ts);
  PhaseIn in;
  in.heights = win_heights;
  in.base_round = win_base;
  in.W = W;
  in.slot_lut = slot_lut;
  in.S = S;
  in.V = V;
  in.pubkeys = pubkeys;
  in.I = G->I;
  in.lane_floor = lane_floor;
  in.max_votes = max_votes;
  in.phase_offset = phase_offset;
  in.pad_cap = pad_cap;
  PhaseOut out;
  out.slots = ph_slots;
  out.mask = ph_mask;
  out.ph_typ = ph_typ;
  out.ph_counts = ph_counts;
  out.ln_pub = ln_pub;
  out.ln_sig = ln_sig;
  out.ln_blocks = ln_blocks;
  out.ln_phase_idx = ln_phase_idx;
  out.ln_inst = ln_inst;
  out.ln_val = ln_val;
  out.ln_real = ln_real;
  out.ln_rows = ln_rows;
  out.meta = out_meta;
  densify_phases(rows, inst, val, hts, rnd, typ, value, ver, in, out);
  return n;
}

// merged FIFO export for the model checker's canonical differential:
// a consistent snapshot (all shard locks held) of the would-be drain
// order, at most `cap` records
int64_t ag_adms_export(void* h, uint8_t* raw, uint8_t* ver,
                       int64_t cap) {
  auto* G = static_cast<AdmShards*>(h);
  std::vector<std::unique_lock<std::mutex>> locks;
  locks.reserve(static_cast<size_t>(G->n_shards));
  for (AdmQ* A : G->shards) locks.emplace_back(A->mu);
  std::vector<size_t> pos(static_cast<size_t>(G->n_shards), 0);
  int64_t k = 0;
  while (k < cap) {
    const AdmQ* best = nullptr;
    size_t bs = 0;
    for (int64_t s = 0; s < G->n_shards; ++s) {
      const AdmQ* A = G->shards[static_cast<size_t>(s)];
      const size_t p = pos[static_cast<size_t>(s)];
      if (p >= A->q.size()) continue;
      const NRec& f = A->q[p];
      if (!best) {
        best = A; bs = static_cast<size_t>(s);
      } else {
        const NRec& b = best->q[pos[bs]];
        if (f.seq < b.seq ||
            (f.seq == b.seq && f.sub_idx < b.sub_idx)) {
          best = A; bs = static_cast<size_t>(s);
        }
      }
    }
    if (!best) break;
    const NRec& r = best->q[pos[bs]++];
    std::memcpy(raw + k * kRecSize, r.raw, kRecSize);
    ver[k] = r.verified;
    ++k;
  }
  return k;
}

}  // extern "C"
