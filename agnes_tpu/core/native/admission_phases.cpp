// Zero-copy native densify (ISSUE 20 tentpole, layer 1): drain the
// admission queue STRAIGHT into the padded per-phase arrays that
// VoteBatcher.build_phases_device would have produced — slot/mask
// planes per (round, typ) phase plus the padded SignedLanes columns
// (widened pubkeys/signatures, pre-packed SHA-512 message blocks,
// phase ids, pad mask) — behind the same single GIL-releasing call as
// the plain drain.  The Python side then only wraps the buffers
// (jnp.asarray) and dispatches: ZERO per-record Python work between
// submit and dispatch.
//
// Conformance discipline: this is a CONSERVATIVE SUBSET of the Python
// build.  densify_phases fills the phase outputs only when the popped
// rows are provably device-verify eligible by the batcher's exact
// rules (_device_verify_eligible + the add_arrays screens reduced to
// the no-drop case):
//
//   - 0 < n <= max_votes            (no _defer_pending split)
//   - every row unverified          (split-rung stays a Python seam)
//   - validator/typ/value in range  (no malformed drops)
//   - height == window height       (no stale-height drops)
//   - 0 <= round - base < W         (no held/past splits)
//   - ONE round across the batch    (the device fast path)
//   - unique (typ, instance, validator) cells
//   - <= 1 distinct non-nil value per instance, and that value is
//     ALREADY interned in the SlotMap's dense LUT (a first-appearance
//     value falls back to Python once, which interns it)
//
// Any violation returns status 0 with the plain columns still filled —
// the wrapper hands them to VoteBatcher.add_arrays and the Python path
// handles the screens/splits it owns.  Because the eligible set is
// exactly the set where the Python build drops nothing, splits
// nothing, interns nothing, and takes the single-round fast path, the
// native fill is leaf-for-leaf identical to what Python would emit
// (tests/test_native_admission.py replays corpus + hostile schedules
// through both).
//
// Lock discipline: rows are popped under the queue mutex and densified
// OUTSIDE it — the [2,I,V] plane fills and per-lane block packing must
// not extend the submit thread's critical section.

#include <cmath>
#include <cstdint>
#include <cstring>
#include <limits>
#include <mutex>
#include <vector>

#include "admission.hpp"

namespace agnes_adm {

namespace {

constexpr int64_t kMaxRound = (int64_t{1} << 31) - 1;    // types.MAX_ROUND
constexpr int64_t kMaxValueId = (int64_t{1} << 31) - 1;  // value_table
constexpr int32_t kVotedNil = -1;                        // tally.VOTED_NIL
constexpr int64_t kMsgLen = 45;                          // VOTE_MSG_LEN

// pack one lane's 128-byte SHA-512 block — byte-for-byte the
// _sha_blocks_np layout: R || A || msg || 0x80 pad || bitlen(872)
void pack_block(const uint8_t* sig_r, const uint8_t* pubkey,
                int64_t typ, int64_t height, int64_t rnd, int64_t value,
                uint32_t* out32) {
  uint8_t buf[128];
  std::memset(buf, 0, sizeof(buf));
  std::memcpy(buf + 0, sig_r, 32);      // R (signature first half)
  std::memcpy(buf + 32, pubkey, 32);    // A (validator pubkey)
  uint8_t* msg = buf + 64;              // 45-byte vote message
  msg[0] = static_cast<uint8_t>(typ & 0xFF);
  const uint64_t h64 = static_cast<uint64_t>(height);
  for (int b = 0; b < 8; ++b)
    msg[1 + b] = static_cast<uint8_t>((h64 >> (8 * b)) & 0xFF);
  const uint32_t r32 = static_cast<uint32_t>(static_cast<int64_t>(rnd));
  for (int b = 0; b < 4; ++b)
    msg[9 + b] = static_cast<uint8_t>((r32 >> (8 * b)) & 0xFF);
  if (value < 0) {
    // nil vote: the value field AND the spare bytes carry 0xFF
    std::memset(msg + 13, 0xFF, kMsgLen - 13);
  } else {
    const uint64_t v64 = static_cast<uint64_t>(value);
    for (int b = 0; b < 8; ++b)
      msg[13 + b] = static_cast<uint8_t>((v64 >> (8 * b)) & 0xFF);
    // msg[21:45] stay zero
  }
  buf[64 + kMsgLen] = 0x80;             // SHA-512 pad start (byte 109)
  buf[126] = 0x03;                      // bit length 872 = 0x368,
  buf[127] = 0x68;                      // big-endian u64 tail
  for (int w = 0; w < 32; ++w)          // big-endian u32 words
    out32[w] = (static_cast<uint32_t>(buf[4 * w]) << 24) |
               (static_cast<uint32_t>(buf[4 * w + 1]) << 16) |
               (static_cast<uint32_t>(buf[4 * w + 2]) << 8) |
               static_cast<uint32_t>(buf[4 * w + 3]);
}

}  // namespace

int densify_phases(const std::vector<NRec>& rows, const int64_t* inst,
                   const int64_t* val, const int64_t* hts,
                   const int64_t* rnd, const int64_t* typ,
                   const int64_t* value, const uint8_t* ver,
                   const PhaseIn& in, const PhaseOut& out) {
  const int64_t n = static_cast<int64_t>(rows.size());
  out.meta[0] = 0;
  out.meta[1] = 0;
  out.meta[2] = 0;
  out.meta[3] = 0;
  out.meta[4] = -1;
  if (n <= 0 || n > in.max_votes) return 0;

  // eligibility pass: screens + the single-round / single-value /
  // known-slot device-verify conditions.  ival memoizes the one
  // non-nil value allowed per instance; islot its interned slot.
  const int64_t r0 = rnd[0];
  if (r0 < 0 || r0 > kMaxRound) return 0;
  std::vector<int64_t> ival(static_cast<size_t>(in.I),
                            std::numeric_limits<int64_t>::min());
  std::vector<int32_t> islot(static_cast<size_t>(in.I), -1);
  bool has_typ[2] = {false, false};
  for (int64_t k = 0; k < n; ++k) {
    if (ver[k]) return 0;                    // pre-verified: Python splits
    const int64_t i = inst[k];
    if (i < 0 || i >= in.I) return 0;        // (queue already screened)
    if (val[k] < 0 || val[k] >= in.V) return 0;
    if (typ[k] < 0 || typ[k] > 1) return 0;
    if (rnd[k] != r0) return 0;              // multi-round: Python path
    if (value[k] > kMaxValueId) return 0;
    if (hts[k] != in.heights[i]) return 0;   // stale: Python drops
    const int64_t w = r0 - in.base_round[i];
    if (w < 0 || w >= in.W) return 0;        // past/held: Python splits
    has_typ[static_cast<size_t>(typ[k])] = true;
    if (value[k] >= 0) {
      const size_t si = static_cast<size_t>(i);
      if (ival[si] == std::numeric_limits<int64_t>::min()) {
        ival[si] = value[k];
        // dense SlotMap lookup: the value must already be interned
        const int64_t* lut = in.slot_lut + i * in.S;
        int32_t s = -1;
        for (int64_t j = 0; j < in.S; ++j)
          if (lut[j] == value[k]) { s = static_cast<int32_t>(j); break; }
        if (s < 0) return 0;                 // first appearance: intern
        islot[si] = s;                       // on the Python path
      } else if (ival[si] != value[k]) {
        return 0;                            // >1 value: device-ineligible
      }
    }
  }

  // phase planes in the Python class order: PREVOTE then PRECOMMIT
  int64_t p_of_typ[2] = {-1, -1};
  int64_t n_phases = 0;
  for (int t = 0; t < 2; ++t)
    if (has_typ[t]) p_of_typ[t] = n_phases++;
  const int64_t plane = in.I * in.V;
  for (int64_t p = 0; p < n_phases; ++p) {
    int32_t* s = out.slots + p * plane;
    for (int64_t c = 0; c < plane; ++c) s[c] = kVotedNil;
    std::memset(out.mask + p * plane, 0, static_cast<size_t>(plane));
    out.ph_counts[p] = 0;
  }
  out.ph_typ[0] = p_of_typ[0] == 0 ? 0 : 1;
  if (n_phases == 2) out.ph_typ[1] = 1;

  // scatter + duplicate-cell screen (the mask doubles as the dedup
  // bitmap — a set bit on arrival means the cell repeats, which is
  // device-ineligible, so bail to Python)
  const int64_t n_pad_floor = in.lane_floor;
  for (int64_t k = 0; k < n; ++k) {
    const int64_t p = p_of_typ[static_cast<size_t>(typ[k])];
    const int64_t cell = inst[k] * in.V + val[k];
    uint8_t* m = out.mask + p * plane + cell;
    if (*m) return 0;
    *m = 1;
    out.slots[p * plane + cell] =
        value[k] < 0 ? kVotedNil : islot[static_cast<size_t>(inst[k])];
    out.ph_counts[p]++;
  }

  // padded lane rung: next pow2 of n, floored at the ladder's min rung
  int64_t n_pad = 1;
  while (n_pad < n) n_pad <<= 1;
  if (n_pad < n_pad_floor) n_pad = n_pad_floor;
  if (n_pad > in.pad_cap) return 0;          // caller under-allocated

  // the Python build concatenates lanes PER PHASE GROUP (cat =
  // _concat(groups)): all PREVOTE rows in arrival order, then all
  // PRECOMMIT rows — phase_idx is contiguous ascending blocks.
  // ln_rows records that lane -> drained-row permutation so the
  // adopter can gather digest/instance/height cache keys in cat
  // order.  Pads are copies of LANE 0 (the first row of the first
  // phase group) pointed at the one-past-the-end phase id.
  {
    int64_t j = 0;
    for (int t = 0; t < 2; ++t) {
      if (!has_typ[t]) continue;
      for (int64_t k = 0; k < n; ++k)
        if (typ[k] == t) out.ln_rows[j++] = k;
    }
  }
  for (int64_t j = 0; j < n_pad; ++j) {
    const int64_t k = out.ln_rows[j < n ? j : 0];
    const NRec& r = rows[static_cast<size_t>(k)];
    const uint8_t* pk = in.pubkeys + val[k] * 32;
    for (int b = 0; b < 32; ++b)
      out.ln_pub[j * 32 + b] = static_cast<int32_t>(pk[b]);
    const uint8_t* sg = r.raw + 32;
    for (int b = 0; b < 64; ++b)
      out.ln_sig[j * 64 + b] = static_cast<int32_t>(sg[b]);
    pack_block(sg, pk, typ[k], hts[k], rnd[k], value[k],
               out.ln_blocks + j * 32);
    out.ln_inst[j] = static_cast<int32_t>(inst[k]);
    out.ln_val[j] = static_cast<int32_t>(val[k]);
    if (j < n) {
      out.ln_phase_idx[j] = static_cast<int32_t>(
          p_of_typ[static_cast<size_t>(typ[k])] + in.phase_offset);
      out.ln_real[j] = 1;
    } else {
      out.ln_phase_idx[j] =
          static_cast<int32_t>(in.phase_offset + n_phases);
      out.ln_real[j] = 0;
    }
  }

  out.meta[0] = 1;
  out.meta[1] = n_phases;
  out.meta[2] = n;
  out.meta[3] = n_pad;
  out.meta[4] = r0;
  return 1;
}

}  // namespace agnes_adm

using namespace agnes_adm;

extern "C" {

// drain-and-densify-to-phases: the plain ag_adm_drain columns are
// ALWAYS filled for the popped records (the Python fallback and the
// evidence log need them either way); when the rows are device-verify
// eligible the phase/lane buffers are filled too and out_meta[0] = 1.
// out_meta = [status, n_phases, n_lanes, n_pad, round].  Rows are
// popped under the queue mutex; parsing and densify run outside it.
// Returns the popped count.
int64_t ag_adm_drain_phases(
    void* h, int64_t n, int64_t* inst, int64_t* val, int64_t* hts,
    int64_t* rnd, int64_t* typ, int64_t* value, uint8_t* sigs,
    uint8_t* ver, uint8_t* out_dig, double* ts,
    const int64_t* win_heights, const int64_t* win_base, int64_t W,
    const int64_t* slot_lut, int64_t S, int64_t V,
    const uint8_t* pubkeys, int64_t lane_floor, int64_t max_votes,
    int64_t phase_offset, int64_t pad_cap, int32_t* ph_slots,
    uint8_t* ph_mask, int64_t* ph_typ, int64_t* ph_counts,
    int32_t* ln_pub, int32_t* ln_sig, uint32_t* ln_blocks,
    int32_t* ln_phase_idx, int32_t* ln_inst, int32_t* ln_val,
    uint8_t* ln_real, int64_t* ln_rows, int64_t* out_meta) {
  auto* A = static_cast<AdmQ*>(h);
  std::vector<NRec> rows;
  {
    std::lock_guard<std::mutex> g(A->mu);
    if (n < 0) n = 0;
    if (n > static_cast<int64_t>(A->q.size()))
      n = static_cast<int64_t>(A->q.size());
    rows.reserve(static_cast<size_t>(n));
    for (int64_t k = 0; k < n; ++k) {
      rows.push_back(A->q.front());
      A->inst_counts[static_cast<size_t>(
          rec_instance(A->q.front().raw))]--;
      A->q.pop_front();
    }
    A->counters[6] += n;
  }
  for (int64_t k = 0; k < n; ++k)
    parse_record(rows[static_cast<size_t>(k)], k, inst, val, hts, rnd,
                 typ, value, sigs, ver, out_dig, ts);
  PhaseIn in;
  in.heights = win_heights;
  in.base_round = win_base;
  in.W = W;
  in.slot_lut = slot_lut;
  in.S = S;
  in.V = V;
  in.pubkeys = pubkeys;
  in.I = A->I;
  in.lane_floor = lane_floor;
  in.max_votes = max_votes;
  in.phase_offset = phase_offset;
  in.pad_cap = pad_cap;
  PhaseOut out;
  out.slots = ph_slots;
  out.mask = ph_mask;
  out.ph_typ = ph_typ;
  out.ph_counts = ph_counts;
  out.ln_pub = ln_pub;
  out.ln_sig = ln_sig;
  out.ln_blocks = ln_blocks;
  out.ln_phase_idx = ln_phase_idx;
  out.ln_inst = ln_inst;
  out.ln_val = ln_val;
  out.ln_real = ln_real;
  out.ln_rows = ln_rows;
  out.meta = out_meta;
  densify_phases(rows, inst, val, hts, rnd, typ, value, ver, in, out);
  return n;
}

}  // extern "C"
