// Native (C++) consensus core: domain types, the pure Tendermint state
// machine, and the per-round vote tally.
//
// Semantic parity contract: this is a third implementation of the same
// machine as agnes_tpu/core/state_machine.py (the Python oracle) and
// agnes_tpu/device/state_machine.py (the JAX plane), all reproducing
// the reference's transition table (reference src/state_machine.rs:
// 183-214) with the documented subtleties (lock rule :239-244,
// commit-from-any-round :211, no-step-change timeouts :287-295).
// The tally applies the SURVEY.md §2.3 fixes (per-value buckets,
// per-validator dedup + equivocation evidence) on top of the
// reference's quorum semantics (round_votes.rs:31-33, :58-66).
// Differential tests: tests/test_native_core.py sweeps this against
// the Python oracle over the full Step x Event x guard space.

#pragma once

#include <cstdint>
#include <map>
#include <set>
#include <vector>

namespace agnes {

// integer codes shared verbatim with core/state_machine.py and
// device/encoding.py
enum class Step : int32_t {
  NewRound = 0, Propose = 1, Prevote = 2, Precommit = 3, Commit = 4
};

enum class EventTag : int32_t {
  NewRound = 0, NewRoundProposer = 1, Proposal = 2, ProposalInvalid = 3,
  PolkaAny = 4, PolkaNil = 5, PolkaValue = 6, PrecommitAny = 7,
  PrecommitValue = 8, RoundSkip = 9, TimeoutPropose = 10,
  TimeoutPrevote = 11, TimeoutPrecommit = 12
};

enum class TimeoutStep : int32_t { Propose = 0, Prevote = 1, Precommit = 2 };

enum class MsgTag : int32_t {
  None = 0, NewRound = 1, Proposal = 2, Vote = 3, Timeout = 4, Decision = 5
};

enum class VoteType : int32_t { Prevote = 0, Precommit = 1 };

constexpr int64_t kNoValue = -1;  // Option::None for value/round fields

struct State {
  int64_t height = 0;
  int64_t round = 0;
  Step step = Step::NewRound;
  bool has_locked = false, has_valid = false;
  int64_t locked_round = kNoValue, locked_value = kNoValue;
  int64_t valid_round = kNoValue, valid_value = kNoValue;
};

struct Event {
  EventTag tag;
  bool has_value = false;
  int64_t value = kNoValue;
  int64_t pol_round = -1;
};

struct Message {
  MsgTag tag = MsgTag::None;
  int64_t round = 0;
  // proposal payload (round = .round)
  int64_t p_value = kNoValue;
  int64_t p_pol_round = -1;
  // vote payload
  VoteType v_typ = VoteType::Prevote;
  bool v_has_value = false;
  int64_t v_value = kNoValue;
  // timeout payload
  TimeoutStep t_step = TimeoutStep::Propose;
  // decision payload
  int64_t d_round = 0, d_value = kNoValue;
};

// the pure transition function (reference state_machine.rs:183-214)
void apply(const State& s, int64_t round, const Event& e,
           State* out_state, Message* out_msg);

// --- vote tally (reference round_votes.rs + SURVEY §2.3 fixes) -------------

enum class ThreshKind : int32_t { Init = 0, Any = 1, Nil = 2, Value = 3 };

// 128-bit products: the raw C ABI accepts arbitrary int64 weights, so
// 3*v / 2*total must not overflow (reference round_votes.rs:31-33 is
// safe only because Rust debug builds trap; here hostile callers reach
// this directly through capi.cpp)
inline bool is_quorum(int64_t v, int64_t total) {
  return static_cast<__int128>(3) * v > static_cast<__int128>(2) * total;
}
inline bool is_one_third(int64_t v, int64_t total) {
  return static_cast<__int128>(3) * v > static_cast<__int128>(total);
}

// framework rounds domain top (types.py MAX_ROUND): round arithmetic
// saturates here on every plane so the int64 host cores and the int32
// device plane stay bit-for-bit at the representable edge
constexpr int64_t kMaxRound = 2147483647;  // 2^31 - 1

// saturating accumulate for weight tallies: hostile extreme weights
// clamp instead of wrapping (wrap could un-cross a crossed quorum)
inline int64_t sat_add(int64_t a, int64_t b) {
  __int128 s = static_cast<__int128>(a) + b;
  if (s > INT64_MAX) return INT64_MAX;
  if (s < INT64_MIN) return INT64_MIN;
  return static_cast<int64_t>(s);
}
inline int64_t sat_sub(int64_t a, int64_t b) {
  __int128 s = static_cast<__int128>(a) - b;
  if (s > INT64_MAX) return INT64_MAX;
  if (s < INT64_MIN) return INT64_MIN;
  return static_cast<int64_t>(s);
}

struct Equivocation {
  int64_t height, round;
  VoteType typ;
  int64_t validator;
  int64_t first_value, second_value;  // kNoValue = nil
};

class VoteCount {
 public:
  explicit VoteCount(int64_t total) : total_(total) {}

  // add weight for value (kNoValue = nil); returns highest threshold,
  // priority Value > Nil > Any > Init (round_votes.rs:58-66)
  ThreshKind add(int64_t value, int64_t weight, int64_t* thresh_value);
  ThreshKind thresh(int64_t* thresh_value) const;

  int64_t seen_weight() const;

 private:
  int64_t total_;
  int64_t nil_ = 0;
  std::map<int64_t, int64_t> weights_;
};

class RoundVotes {
 public:
  RoundVotes(int64_t height, int64_t round, int64_t total)
      : height_(height), round_(round), total_(total),
        prevotes_(total), precommits_(total) {}

  // validator = kNoValue for identity-free votes (no dedup, reference
  // parity); value = kNoValue for nil
  ThreshKind add_vote(VoteType typ, int64_t validator, int64_t value,
                      int64_t weight, int64_t* thresh_value);

  int64_t skip_weight() const;
  const std::vector<Equivocation>& equivocations() const { return equiv_; }

 private:
  int64_t height_, round_, total_;
  VoteCount prevotes_, precommits_;
  // (validator, typ) -> (value, weight) of the first counted vote
  std::map<std::pair<int64_t, int32_t>, std::pair<int64_t, int64_t>> seen_;
  std::set<std::pair<int64_t, int32_t>> flagged_;
  int64_t anon_weight_[2] = {0, 0};
  std::vector<Equivocation> equiv_;
};

// --- validator set (reference validators.rs intent, §2.6) ------------------

struct Validator {
  uint8_t public_key[32];
  int64_t voting_power;
};

class ValidatorSet {
 public:
  // sorted by address (= public key, validators.rs:15-17), deduplicated
  explicit ValidatorSet(std::vector<Validator> vals);

  void add(const Validator& v);
  bool update(const Validator& v);   // by pubkey; true if found
  bool remove(const uint8_t pk[32]);

  int64_t total_power() const;
  const std::vector<Validator>& validators() const { return vals_; }
  // index of pubkey in sorted order, -1 if absent
  int64_t index_of(const uint8_t pk[32]) const;
  // 32-byte hash of the set (SHA-512/256 of the sorted entries)
  void hash(uint8_t out[32]) const;

 private:
  void sort_dedup();
  std::vector<Validator> vals_;
};

// Tendermint-style weighted round-robin proposer selection — the exact
// algorithm of core/validators.py ProposerRotation (one shared
// sequence feeds the host planes and the device proposer table, so the
// implementations MUST agree; tests/test_native_core.py checks the
// sequences match step for step).  Stateful: call step() once per
// (height, round) in order.  Holds a non-owning pointer to the set.
class ProposerRotation {
 public:
  explicit ProposerRotation(const ValidatorSet* set) : set_(set) {}

  // advance one slot; returns the proposer's index in the current
  // address-sorted set
  int64_t step();

 private:
  const ValidatorSet* set_;
  std::map<std::vector<uint8_t>, int64_t> priorities_;  // by address
};

}  // namespace agnes
