// SHA-512 (FIPS 180-4) for the native core: validator-set hashing and
// the Ed25519 sign/verify challenge hash.  Written from the spec; the
// round constants are generated at build time by native_build.py from
// their definition (frac parts of cube roots of the first 80 primes)
// into sha512_k.inc — the same generator the JAX layer uses, so all
// three implementations share one constant source.
#pragma once

#include <cstddef>
#include <cstdint>

namespace agnes {

struct Sha512 {
  uint64_t h[8];
  uint8_t buf[128];
  uint64_t len = 0;   // total bytes absorbed

  Sha512();
  void update(const uint8_t* data, size_t n);
  void final(uint8_t out[64]);
};

void sha512(const uint8_t* data, size_t n, uint8_t out[64]);

// SHA-256 (FIPS 180-4 §6.2), one-shot: the serve plane's dedup-cache
// digest (serve/cache.VerifiedCache keys on the SHA-256 of the
// 96-byte wire record).  Same generated-constant source as SHA-512:
// kK256/kH256 land in sha512_k.inc from their FIPS definitions (frac
// parts of cube/square roots of the first primes), asserted against
// the published first/last words at generation time.
void sha256(const uint8_t* data, size_t n, uint8_t out[32]);

}  // namespace agnes
