// Implementation of the native consensus core.  See core.hpp for the
// parity contract and reference citations.

#include "core.hpp"

#include <algorithm>
#include <cstring>

#include "sha512.hpp"

namespace agnes {

namespace {

Step next_step(Step s) {
  switch (s) {
    case Step::NewRound: return Step::Propose;
    case Step::Propose: return Step::Prevote;
    case Step::Prevote: return Step::Precommit;
    default: return s;  // saturates (state_machine.rs:58-66)
  }
}

Message msg_new_round(int64_t r) {
  Message m; m.tag = MsgTag::NewRound; m.round = r; return m;
}

Message msg_proposal(int64_t r, int64_t value, int64_t pol_round) {
  Message m; m.tag = MsgTag::Proposal; m.round = r;
  m.p_value = value; m.p_pol_round = pol_round; return m;
}

Message msg_vote(VoteType t, int64_t r, bool has_value, int64_t value) {
  Message m; m.tag = MsgTag::Vote; m.round = r;
  m.v_typ = t; m.v_has_value = has_value;
  m.v_value = has_value ? value : kNoValue; return m;
}

Message msg_timeout(int64_t r, TimeoutStep st) {
  Message m; m.tag = MsgTag::Timeout; m.round = r; m.t_step = st; return m;
}

Message msg_decision(int64_t r, int64_t value) {
  Message m; m.tag = MsgTag::Decision; m.round = r;
  m.d_round = r; m.d_value = value; return m;
}

}  // namespace

// the transition actions (reference state_machine.rs:216-322)

static void propose(State s, int64_t v, State* os, Message* om) {
  s.step = next_step(s.step);
  int64_t value = v, pol_round = -1;
  if (s.has_valid) { value = s.valid_value; pol_round = s.valid_round; }
  *os = s; *om = msg_proposal(s.round, value, pol_round);     // spec 11/14
}

static void prevote(State s, int64_t vr, int64_t proposed, State* os,
                    Message* om) {
  s.step = next_step(s.step);
  // lock rule (state_machine.rs:239-244, spec 22/28)
  bool vote_value;
  if (!s.has_locked) vote_value = true;                // not locked
  else if (s.locked_round <= vr) vote_value = true;    // unlock
  else if (s.locked_value == proposed) vote_value = true;  // same value
  else vote_value = false;                             // locked elsewhere: nil
  *os = s;
  *om = msg_vote(VoteType::Prevote, s.round, vote_value, proposed);
}

static void prevote_nil(State s, State* os, Message* om) {
  s.step = next_step(s.step);
  *os = s; *om = msg_vote(VoteType::Prevote, s.round, false, kNoValue);
}

static void precommit(State s, int64_t v, State* os, Message* om) {
  // sets BOTH locked and valid (state_machine.rs:261-264, spec 36)
  s.has_locked = true; s.locked_round = s.round; s.locked_value = v;
  s.has_valid = true; s.valid_round = s.round; s.valid_value = v;
  s.step = next_step(s.step);
  *os = s; *om = msg_vote(VoteType::Precommit, s.round, true, v);
}

static void precommit_nil(State s, State* os, Message* om) {
  s.step = next_step(s.step);
  *os = s; *om = msg_vote(VoteType::Precommit, s.round, false, kNoValue);
}

static void schedule_timeout_propose(State s, State* os, Message* om) {
  s.step = next_step(s.step);
  *os = s; *om = msg_timeout(s.round, TimeoutStep::Propose);
}

static void schedule_timeout_prevote(const State& s, State* os, Message* om) {
  // no step change (state_machine.rs:287-289)
  *os = s; *om = msg_timeout(s.round, TimeoutStep::Prevote);
}

static void schedule_timeout_precommit(const State& s, State* os,
                                       Message* om) {
  // no step change (state_machine.rs:293-295)
  *os = s; *om = msg_timeout(s.round, TimeoutStep::Precommit);
}

static void set_valid_value(State s, int64_t v, State* os, Message* om) {
  // only valid, no message (state_machine.rs:304-306, spec 36/42)
  s.has_valid = true; s.valid_round = s.round; s.valid_value = v;
  *os = s; om->tag = MsgTag::None;
}

static void round_skip(State s, int64_t r, State* os, Message* om) {
  s.round = r; s.step = Step::NewRound;   // set_round (state_machine.rs:46-52)
  *os = s; *om = msg_new_round(r);
}

static void commit(State s, int64_t r, int64_t v, State* os, Message* om) {
  // state round untouched; Decision carries the event round
  // (state_machine.rs:320-322, spec 49)
  s.step = Step::Commit;
  *os = s; *om = msg_decision(r, v);
}

void apply(const State& s, int64_t round, const Event& e, State* os,
           Message* om) {
  const bool eqr = s.round == round;
  const Step st = s.step;
  const EventTag tag = e.tag;
  om->tag = MsgTag::None;

  // arm order matches the reference match expression exactly
  // (state_machine.rs:185-213)
  if (st == Step::NewRound && tag == EventTag::NewRoundProposer && eqr)
    return propose(s, e.value, os, om);                          // 11/14
  if (st == Step::NewRound && tag == EventTag::NewRound && eqr)
    return schedule_timeout_propose(s, os, om);                  // 11/20
  if (st == Step::Propose && tag == EventTag::Proposal && eqr &&
      e.pol_round >= -1 && e.pol_round < s.round)
    return prevote(s, e.pol_round, e.value, os, om);             // 22, 28
  if (st == Step::Propose && tag == EventTag::ProposalInvalid && eqr)
    return prevote_nil(s, os, om);                               // 22/25
  if (st == Step::Propose && tag == EventTag::TimeoutPropose && eqr)
    return prevote_nil(s, os, om);                               // 57
  if (st == Step::Prevote && tag == EventTag::PolkaAny && eqr)
    return schedule_timeout_prevote(s, os, om);                  // 34
  if (st == Step::Prevote && tag == EventTag::PolkaNil && eqr)
    return precommit_nil(s, os, om);                             // 44
  if (st == Step::Prevote && tag == EventTag::PolkaValue && eqr)
    return precommit(s, e.value, os, om);                        // 36/37
  if (st == Step::Prevote && tag == EventTag::TimeoutPrevote && eqr)
    return precommit_nil(s, os, om);                             // 61
  if (st == Step::Precommit && tag == EventTag::PolkaValue && eqr)
    return set_valid_value(s, e.value, os, om);                  // 36/42
  if (st == Step::Commit) { *os = s; return; }                   // absorb
  if (tag == EventTag::PrecommitAny && eqr)
    return schedule_timeout_precommit(s, os, om);                // 47
  if (tag == EventTag::TimeoutPrecommit && eqr)
    return round_skip(s, std::min(sat_add(round, 1), kMaxRound),
                      os, om);                                   // 65
  if (tag == EventTag::RoundSkip && s.round < round)
    return round_skip(s, round, os, om);                         // 55
  if (tag == EventTag::PrecommitValue)                           // no guard!
    return commit(s, round, e.value, os, om);                    // 49

  *os = s;  // no-op
}

// --- tally -----------------------------------------------------------------

ThreshKind VoteCount::add(int64_t value, int64_t weight,
                          int64_t* thresh_value) {
  if (value == kNoValue) nil_ = sat_add(nil_, weight);
  else {
    int64_t& w = weights_[value];
    w = sat_add(w, weight);
  }
  return thresh(thresh_value);
}

int64_t VoteCount::seen_weight() const {
  int64_t w = nil_;
  for (const auto& kv : weights_) w = sat_add(w, kv.second);
  return w;
}

ThreshKind VoteCount::thresh(int64_t* thresh_value) const {
  // highest-weight value with a quorum (ties only possible in
  // adversarial identity-free streams)
  int64_t best = kNoValue, best_w = -1;
  for (const auto& kv : weights_)
    if (is_quorum(kv.second, total_) && kv.second > best_w) {
      best = kv.first; best_w = kv.second;
    }
  if (best != kNoValue) { *thresh_value = best; return ThreshKind::Value; }
  *thresh_value = kNoValue;
  if (is_quorum(nil_, total_)) return ThreshKind::Nil;
  if (is_quorum(seen_weight(), total_)) return ThreshKind::Any;
  return ThreshKind::Init;
}

ThreshKind RoundVotes::add_vote(VoteType typ, int64_t validator,
                                int64_t value, int64_t weight,
                                int64_t* thresh_value) {
  // normalize the tag to its CLASS before doing anything keyed by it:
  // every non-prevote tag routes to precommits_, so a hostile caller
  // replaying distinct raw tags must not get distinct seen_ keys (that
  // would double-count one validator's weight into a forged quorum)
  int32_t cls = (typ == VoteType::Prevote) ? 0 : 1;
  VoteCount& count = cls == 0 ? prevotes_ : precommits_;
  if (validator != kNoValue) {
    auto key = std::make_pair(validator, cls);
    auto it = seen_.find(key);
    if (it != seen_.end()) {
      // duplicate or conflict: not counted; conflict -> one evidence
      // record per (validator, type)
      if (it->second.first != value && !flagged_.count(key)) {
        flagged_.insert(key);
        equiv_.push_back({height_, round_,
                          static_cast<VoteType>(cls), validator,
                          it->second.first, value});
      }
      return count.thresh(thresh_value);
    }
    seen_[key] = {value, weight};
  } else {
    int64_t& aw = anon_weight_[cls];
    aw = sat_add(aw, weight);
  }
  return count.add(value, weight, thresh_value);
}

int64_t RoundVotes::skip_weight() const {
  // distinct voters count once whatever the type; identity-free weight
  // contributes max of the two classes (mirrors core/round_votes.py)
  std::map<int64_t, int64_t> by_validator;
  for (const auto& kv : seen_) {
    int64_t v = kv.first.first;
    int64_t w = kv.second.second;
    auto it = by_validator.find(v);
    if (it == by_validator.end() || it->second < w) by_validator[v] = w;
  }
  int64_t sum = std::max(anon_weight_[0], anon_weight_[1]);
  for (const auto& kv : by_validator) sum = sat_add(sum, kv.second);
  return sum;
}

// --- validator set ---------------------------------------------------------

ValidatorSet::ValidatorSet(std::vector<Validator> vals)
    : vals_(std::move(vals)) {
  sort_dedup();
}

void ValidatorSet::sort_dedup() {
  // sorted by address = public key (validators.rs:15-17, :49-55 intent).
  // stable sort + keep-first makes duplicate resolution deterministic:
  // the LAST pushed entry wins (push order is reversed first), matching
  // the Python ValidatorSet's replace-on-duplicate semantics.
  std::reverse(vals_.begin(), vals_.end());
  std::stable_sort(vals_.begin(), vals_.end(),
                   [](const Validator& a, const Validator& b) {
                     return std::memcmp(a.public_key, b.public_key, 32) < 0;
                   });
  vals_.erase(std::unique(vals_.begin(), vals_.end(),
                          [](const Validator& a, const Validator& b) {
                            return std::memcmp(a.public_key, b.public_key,
                                               32) == 0;
                          }),
              vals_.end());
}

void ValidatorSet::add(const Validator& v) {
  // latest wins on duplicate pubkey (mirrors the Python set's replace)
  int64_t i = index_of(v.public_key);
  if (i >= 0) {
    vals_[static_cast<size_t>(i)].voting_power = v.voting_power;
    return;
  }
  vals_.push_back(v);
  sort_dedup();
}

bool ValidatorSet::update(const Validator& v) {
  int64_t i = index_of(v.public_key);
  if (i < 0) return false;
  vals_[static_cast<size_t>(i)].voting_power = v.voting_power;
  return true;
}

bool ValidatorSet::remove(const uint8_t pk[32]) {
  int64_t i = index_of(pk);
  if (i < 0) return false;
  vals_.erase(vals_.begin() + static_cast<size_t>(i));
  return true;
}

int64_t ValidatorSet::total_power() const {
  int64_t t = 0;
  for (const auto& v : vals_) t = sat_add(t, v.voting_power);
  return t;
}

int64_t ValidatorSet::index_of(const uint8_t pk[32]) const {
  auto it = std::lower_bound(
      vals_.begin(), vals_.end(), pk,
      [](const Validator& a, const uint8_t* key) {
        return std::memcmp(a.public_key, key, 32) < 0;
      });
  if (it == vals_.end() || std::memcmp(it->public_key, pk, 32) != 0)
    return -1;
  return it - vals_.begin();
}

int64_t ProposerRotation::step() {
  // exact mirror of core/validators.py ProposerRotation.step():
  // prune removed validators, init newcomers at 0, add each validator's
  // power, pick the max priority (ties -> lower index), subtract total.
  const auto& vals = set_->validators();
  if (vals.empty()) return -1;
  std::map<std::vector<uint8_t>, int64_t> next;
  for (const auto& v : vals) {
    std::vector<uint8_t> addr(v.public_key, v.public_key + 32);
    auto it = priorities_.find(addr);
    next[std::move(addr)] = (it == priorities_.end()) ? 0 : it->second;
  }
  priorities_ = std::move(next);
  for (const auto& v : vals) {
    int64_t& p =
        priorities_[std::vector<uint8_t>(v.public_key, v.public_key + 32)];
    p = sat_add(p, v.voting_power);
  }
  int64_t best = 0;
  int64_t best_p = INT64_MIN;
  for (size_t i = 0; i < vals.size(); ++i) {
    int64_t p = priorities_[std::vector<uint8_t>(
        vals[i].public_key, vals[i].public_key + 32)];
    if (p > best_p) { best_p = p; best = static_cast<int64_t>(i); }
  }
  int64_t& bp = priorities_[std::vector<uint8_t>(
      vals[best].public_key, vals[best].public_key + 32)];
  bp = sat_sub(bp, set_->total_power());
  return best;
}

void ValidatorSet::hash(uint8_t out[32]) const {
  // SHA-512/256-style: SHA-512 over the sorted (pubkey || power_le)
  // entries, truncated to 32 bytes
  std::vector<uint8_t> buf;
  buf.reserve(vals_.size() * 40);
  for (const auto& v : vals_) {
    buf.insert(buf.end(), v.public_key, v.public_key + 32);
    uint64_t p = static_cast<uint64_t>(v.voting_power);
    for (int i = 0; i < 8; ++i) buf.push_back((p >> (8 * i)) & 0xFF);
  }
  uint8_t digest[64];
  sha512(buf.data(), buf.size(), digest);
  std::memcpy(out, digest, 32);
}

}  // namespace agnes
