// Native ingestion event loop: packed wire votes -> dense device phases.
//
// The C++ twin of bridge/ingest.py's VoteBatcher — the "host driver
// concurrency" slot of SURVEY.md §2.7 ("C++ event loop feeding device
// batches; double-buffered host<->device queues").  The reference's
// analogue is the one-vote-at-a-time ConsensusExecutor::execute loop
// (reference consensus_executor.rs:24-49); here the loop is a batch
// pipeline over a packed 96-byte wire record:
//
//   off  0  u32 instance        off 20  u8  typ (0 prevote, 1 precommit)
//   off  4  u32 validator       off 21  u8  flags (bit0: has_value)
//   off  8  i64 height          off 22  u16 (pad)
//   off 16  i32 round           off 24  i64 value
//                               off 32  u8  signature[64]
//
// Tick protocol (mirrors VoteBatcher exactly; differential-tested in
// tests/test_native_ingest.py):
//   sync(base_round, heights)      adopt device window/heights; held
//                                  future-round votes re-enter
//   push(records, n)               parse + screen + window discipline
//   n = stage()                    snapshot pending for verification
//   fill_verify_inputs(...)        -> pub/sig/sha-block arrays for the
//                                  TPU batch-verify kernel
//   apply_verdicts(ok[n])          drop failed lanes (or pass ok=NULL
//                                  for the unsigned path)
//   emit()                         dedup + layer + intern + scatter
//                                  into the CURRENT emit buffer set
//   phase(k, ...)                  pointers into that set (valid until
//                                  the emit after next: double buffer)
//
// Past (rotated-out) rounds fall back to the host tally — the exact
// RoundVotes core (per-value buckets, dedup, equivocation evidence) —
// and late +2/3 precommit-value quorums surface through drain_events
// because commit-from-any-round (reference state_machine.rs:211) must
// fire no matter how late the quorum assembles.

#include <algorithm>
#include <array>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <thread>
#include <tuple>
#include <unordered_map>
#include <vector>

#include "core.hpp"

namespace {

constexpr int64_t kNil = -1;          // value encoding of a nil vote
constexpr int32_t kVotedNil = -1;     // device slot encoding (tally.py)
constexpr int64_t kMaxValue = (int64_t{1} << 31);  // value ids are 31-bit
// rounds domain top (types.py MAX_ROUND / core.hpp kMaxRound): the
// screen must bound rounds exactly like the numpy bridge or the two
// ingest paths diverge on hostile wide rounds
constexpr int64_t kMaxRound = (int64_t{1} << 31) - 1;
constexpr int kRecSize = 96;

// reserve that preserves geometric growth (an exact-size reserve on
// every batch would force a full realloc+copy per call: O(n^2))
template <typename T>
inline void grow_reserve(std::vector<T>& v, size_t add) {
  size_t want = v.size() + add;
  if (v.capacity() < want)
    v.reserve(std::max(want, v.capacity() * 2));
}

struct Rec {
  int64_t instance, validator, height, round, typ, value;
  uint8_t sig[64];
  uint64_t arrival;                   // global order for stable layering
};

struct Phase {
  int32_t round, typ;
  int64_t n_votes;
  std::vector<int32_t> slots;        // [I*V]
  std::vector<uint8_t> mask;         // [I*V]
};

struct EmitSet {
  // phases are pooled: `used` counts the live prefix, buffers behind
  // it keep their capacity across emits (no realloc churn)
  std::vector<Phase> phases;
  size_t used = 0;

  Phase& acquire(int64_t cells) {
    if (used == phases.size()) phases.emplace_back();
    Phase& ph = phases[used++];
    ph.n_votes = 0;
    ph.slots.assign(static_cast<size_t>(cells), kVotedNil);
    ph.mask.assign(static_cast<size_t>(cells), 0);
    return ph;
  }
};

struct Loop {
  int64_t I, V, W, S;
  bool require_verify;
  std::vector<int64_t> heights, base_round;   // [I]
  std::vector<uint8_t> pubkeys;               // [V*32]
  std::vector<int64_t> powers;                // [V]
  int64_t total_power;

  using Block = std::shared_ptr<std::vector<Rec>>;

  std::vector<Rec> pending;      // parsed + malformed-screened; height
                                 // and window screens run at stage()
                                 // against the LAST-SYNCED state, the
                                 // same moment VoteBatcher screens in
                                 // build_phases — push-time screening
                                 // would drop early next-height votes
                                 // the numpy path keeps
  std::vector<Rec> staged;       // snapshot awaiting verdicts
  std::vector<Block> ready;      // verified (or unsigned), pre-emit —
                                 // BLOCKS shared with the log: the
                                 // verdict stage moves whole batches
                                 // instead of copying per record (the
                                 // per-rec copy was the pipeline's
                                 // bandwidth bottleneck)
  std::vector<Rec> held;         // future-round hold-back (capped:
                                 // filled before signature check, so
                                 // unbounded growth would be an
                                 // unauthenticated memory-exhaustion
                                 // vector)
  int64_t held_cap = 0;
  std::vector<Block> log;        // verified votes (slashable evidence)

  // per-instance value-id -> dense slot (bridge/value_table.py
  // SlotMap).  Flat [I*S] arrays, linear-scanned: S is tiny (4-8), so
  // 2-3 cached compares beat a hash lookup — this is the per-vote hot
  // path of the fast lane.  slot k of instance i = slot_vals[i*S + k].
  std::vector<int64_t> slot_vals;     // [I*S]
  std::vector<int32_t> slot_count;    // [I]

  // host fallback tallies for past/overflow votes, keyed
  // (instance, height, round) — never mixes heights into one quorum
  std::map<std::tuple<int64_t, int64_t, int64_t>, agnes::RoundVotes>
      host_tally;
  // (instance, height, round, value) late precommit-value quorums
  std::vector<std::array<int64_t, 4>> events;

  uint64_t arrivals = 0;
  int64_t rejected_malformed = 0;
  int64_t dropped_stale_height = 0;
  int64_t rejected_signature = 0;
  int64_t overflow_votes = 0;
  int64_t dropped_held_overflow = 0;

  EmitSet sets[2];
  int cur = 0;

  // epoch-stamped cell occupancy: fast-path detection without a
  // per-emit O(I*V) clear
  std::vector<uint64_t> cell_epoch;
  uint64_t epoch = 0;

  // --- async ingestion (the actual host-driver concurrency of
  // SURVEY.md §2.7: a worker thread parses + malformed-screens inbound
  // wire buffers while the tick thread drives verify/emit/device).
  // `mu` guards exactly the state both threads touch: inbox, pending,
  // arrivals, rejected_malformed, and the lifecycle flags.  Everything
  // else (staged/held/slots/log/emit sets) is tick-thread-only.
  std::mutex mu;
  std::condition_variable cv_in;    // worker: work available / stop
  std::condition_variable cv_idle;  // flush(): queue drained
  std::deque<std::vector<uint8_t>> inbox;
  int64_t inbox_recs = 0;           // records queued, not yet in pending
  bool worker_busy = false;
  bool stop_worker = false;
  std::thread worker;               // spawned lazily on first push_async

  ~Loop() {
    if (worker.joinable()) {
      {
        std::lock_guard<std::mutex> g(mu);
        stop_worker = true;
      }
      cv_in.notify_all();
      worker.join();
    }
  }
};

void parse_rec(const uint8_t* p, Rec* r);            // defined below
inline bool rec_malformed(const Loop* L, const Rec& r);

// worker thread: pop one wire buffer at a time, parse + screen OFF the
// lock, then append to pending in FIFO order (arrival stamps are
// assigned under the lock, so layering order == push_async order,
// matching the synchronous path exactly)
void ingest_worker_main(Loop* L) {
  std::unique_lock<std::mutex> lk(L->mu);
  for (;;) {
    L->cv_in.wait(lk, [&] { return L->stop_worker || !L->inbox.empty(); });
    if (L->inbox.empty()) return;    // stop requested and drained
    std::vector<uint8_t> buf = std::move(L->inbox.front());
    L->inbox.pop_front();
    L->worker_busy = true;
    lk.unlock();

    const int64_t n = static_cast<int64_t>(buf.size()) / kRecSize;
    std::vector<Rec> local;
    local.reserve(static_cast<size_t>(n));
    int64_t malformed = 0;
    for (int64_t k = 0; k < n; ++k) {
      Rec r;
      parse_rec(buf.data() + k * kRecSize, &r);
      if (rec_malformed(L, r))       // dims are immutable: lock-free read
        ++malformed;
      else
        local.push_back(r);
    }

    lk.lock();
    grow_reserve(L->pending, local.size());
    for (Rec& r : local) {
      r.arrival = L->arrivals++;
      L->pending.push_back(r);
    }
    L->rejected_malformed += malformed;
    L->inbox_recs -= n;
    L->worker_busy = false;
    if (L->inbox.empty()) L->cv_idle.notify_all();
  }
}

void host_tally_add(Loop* L, const Rec& r) {
  auto key = std::make_tuple(r.instance, r.height, r.round);
  auto it = L->host_tally.find(key);
  if (it == L->host_tally.end())
    it = L->host_tally
             .emplace(std::piecewise_construct,
                      std::forward_as_tuple(key),
                      std::forward_as_tuple(r.height, r.round,
                                            L->total_power))
             .first;
  int64_t tv = agnes::kNoValue;
  int64_t w = (r.validator >= 0 && r.validator < L->V)
                  ? L->powers[static_cast<size_t>(r.validator)]
                  : 1;
  auto typ = r.typ == 0 ? agnes::VoteType::Prevote
                        : agnes::VoteType::Precommit;
  auto kind = it->second.add_vote(typ, r.validator,
                                  r.value == kNil ? agnes::kNoValue
                                                  : r.value,
                                  w, &tv);
  if (r.typ == 1 && kind == agnes::ThreshKind::Value)
    L->events.push_back({r.instance, r.height, r.round, tv});
}

// slot interning in ascending (instance, value) order — the same order
// VoteBatcher._intern_slots assigns, so slot numbering matches exactly
inline int32_t slot_lookup(const Loop* L, int64_t inst, int64_t value) {
  const int64_t* base = L->slot_vals.data() + inst * L->S;
  int32_t n = L->slot_count[static_cast<size_t>(inst)];
  for (int32_t k = 0; k < n; ++k)
    if (base[k] == value) return k;
  return kVotedNil;                    // not interned
}

inline int32_t slot_for(Loop* L, int64_t inst, int64_t value) {
  int32_t s = slot_lookup(L, inst, value);
  if (s != kVotedNil) return s;
  int32_t& n = L->slot_count[static_cast<size_t>(inst)];
  if (n >= L->S) return kVotedNil - 1;
  L->slot_vals[static_cast<size_t>(inst * L->S + n)] = value;
  return n++;
}

// wire-record layout (the module-top comment) in ONE place: push,
// evidence, and the snapshot export/import all share these
void pack_rec(const Rec& r, uint8_t* p) {
  std::memset(p, 0, kRecSize);
  uint32_t u32 = static_cast<uint32_t>(r.instance);
  std::memcpy(p + 0, &u32, 4);
  u32 = static_cast<uint32_t>(r.validator);
  std::memcpy(p + 4, &u32, 4);
  std::memcpy(p + 8, &r.height, 8);
  int32_t i32 = static_cast<int32_t>(r.round);
  std::memcpy(p + 16, &i32, 4);
  p[20] = static_cast<uint8_t>(r.typ);
  p[21] = r.value == kNil ? 0 : 1;
  int64_t v = r.value == kNil ? 0 : r.value;
  std::memcpy(p + 24, &v, 8);
  std::memcpy(p + 32, r.sig, 64);
}

void parse_rec(const uint8_t* p, Rec* r) {
  uint32_t u32;
  std::memcpy(&u32, p + 0, 4);  r->instance = u32;
  std::memcpy(&u32, p + 4, 4);  r->validator = u32;
  std::memcpy(&r->height, p + 8, 8);
  int32_t i32;
  std::memcpy(&i32, p + 16, 4); r->round = i32;
  r->typ = p[20];
  bool has_value = (p[21] & 1) != 0;
  std::memcpy(&r->value, p + 24, 8);
  if (!has_value || r->value < 0) r->value = kNil;
  std::memcpy(r->sig, p + 32, 64);
}

// the malformed screen every ingress shares (push AND snapshot import
// — a corrupted snapshot must not inject records push would reject)
inline bool rec_malformed(const Loop* L, const Rec& r) {
  return r.instance >= L->I || r.validator >= L->V || r.round < 0 ||
         r.round > kMaxRound || r.typ > 1 || r.value >= kMaxValue;
}

}  // namespace

extern "C" {

void* ag_ing_new(int64_t I, int64_t V, int64_t W, int64_t S,
                 const uint8_t* pubkeys /* V*32 or NULL */,
                 const int64_t* powers /* V or NULL */) {
  // hostile-dimension screen: this is a raw C ABI, so negative or huge
  // dims must fail closed (NULL) instead of throwing bad_alloc across
  // the extern-C boundary or overflowing the int64 cell math below
  constexpr int64_t kDimMax = int64_t{1} << 31;
  constexpr int64_t kCellMax = int64_t{1} << 40;
  if (I <= 0 || V <= 0 || W <= 0 || S <= 0 || I > kDimMax ||
      V > kDimMax || W > (int64_t{1} << 20) || S > (int64_t{1} << 20) ||
      I > kCellMax / V || I > kCellMax / S)
    return nullptr;
  try {
    auto L = std::make_unique<Loop>();
    L->I = I; L->V = V; L->W = W; L->S = S;
    L->require_verify = pubkeys != nullptr;
    // cap the pre-verification hold-back queue at a couple of full
    // [I, V] ticks (the legitimate future-round working set), floor
    // 64k — see ag_ing_set_held_cap
    L->held_cap = std::max<int64_t>(65536, 2 * I * V);
    L->heights.assign(static_cast<size_t>(I), 0);
    L->base_round.assign(static_cast<size_t>(I), 0);
    if (pubkeys)
      L->pubkeys.assign(pubkeys, pubkeys + V * 32);
    if (powers)
      L->powers.assign(powers, powers + V);
    else
      L->powers.assign(static_cast<size_t>(V), 1);
    L->total_power = 0;
    for (int64_t p : L->powers)
      L->total_power = agnes::sat_add(L->total_power, p);
    L->slot_vals.assign(static_cast<size_t>(I * S), agnes::kNoValue);
    L->slot_count.assign(static_cast<size_t>(I), 0);
    return L.release();
  } catch (...) {
    return nullptr;
  }
}

// bound on the pre-verify future-round hold-back queue (records);
// cap <= 0 resets to the construction default
void ag_ing_set_held_cap(void* h, int64_t cap) {
  auto* L = static_cast<Loop*>(h);
  L->held_cap = cap > 0 ? cap : std::max<int64_t>(65536, 2 * L->I * L->V);
}

// the enforced cap (single source of truth: wrappers/snapshots read
// it back instead of re-deriving the default formula)
int64_t ag_ing_get_held_cap(void* h) {
  return static_cast<Loop*>(h)->held_cap;
}

// validator-set epoch (reference validators.rs:38-46 intent, SURVEY
// §2.6 "re-uploaded on set changes"): swap the pubkey table and/or
// voting powers AT A HEIGHT BOUNDARY — call right after the sync that
// advanced heights (which already dropped the old heights' host
// tallies), from the tick thread, with no staged lanes in flight.
// NULL leaves a table unchanged; a power of 0 models removal (the
// device shape is static).  Returns 0, or -1 for a pubkey upload on a
// loop constructed unsigned (verification policy is construction-time).
int64_t ag_ing_set_validators(void* h, const uint8_t* pubkeys,
                              const int64_t* powers) {
  auto* L = static_cast<Loop*>(h);
  if (pubkeys) {
    if (!L->require_verify) return -1;
    L->pubkeys.assign(pubkeys, pubkeys + L->V * 32);
  }
  if (powers) {
    L->powers.assign(powers, powers + L->V);
    L->total_power = 0;
    for (int64_t p : L->powers)
      L->total_power = agnes::sat_add(L->total_power, p);
  }
  return 0;
}

void ag_ing_free(void* h) { delete static_cast<Loop*>(h); }

// adopt device window bases + heights; held votes re-enter pending
// unconditionally (the next stage() re-screens them against the new
// state — exactly when VoteBatcher.sync_device + build_phases do)
void ag_ing_sync(void* h, const int64_t* base_round,
                 const int64_t* heights) {
  auto* L = static_cast<Loop*>(h);
  for (int64_t i = 0; i < L->I; ++i) {
    if (heights[i] > L->heights[static_cast<size_t>(i)]) {
      L->slot_count[static_cast<size_t>(i)] = 0;
      // clear the values too: the snapshot export derives counts from
      // the kNoValue sentinel, so stale entries would resurrect
      // pre-advance slots on restore
      std::fill_n(L->slot_vals.begin() + i * L->S,
                  static_cast<size_t>(L->S), agnes::kNoValue);
      // decided heights can never commit again: drop their host tallies
      for (auto it = L->host_tally.begin(); it != L->host_tally.end();) {
        if (std::get<0>(it->first) == i &&
            std::get<1>(it->first) < heights[i])
          it = L->host_tally.erase(it);
        else
          ++it;
      }
    }
    L->heights[static_cast<size_t>(i)] = heights[i];
    // the device reports window bases >= 0; clamp hostile values so
    // round-window arithmetic downstream cannot overflow int64
    L->base_round[static_cast<size_t>(i)] =
        base_round[i] < 0 ? 0 : base_round[i];
  }
  if (!L->held.empty()) {
    // pending is shared with the async worker; held is tick-only
    std::lock_guard<std::mutex> g(L->mu);
    grow_reserve(L->pending, L->held.size());
    for (auto& r : L->held) L->pending.push_back(r);
    L->held.clear();
  }
}

// parse + malformed screen; returns count accepted into pending
// (height/window screens run at stage(); rejects are counted on the
// handle).  Takes the async mutex: pending/arrivals/rejected_malformed
// are shared with the worker thread when push_async is in use — and
// DRAINS the inbox first, so a push() after push_async() stamps its
// arrivals after the queued buffers' (first-vote-wins dedup and
// evidence order must match the all-synchronous sequence exactly).
int64_t ag_ing_push(void* h, const uint8_t* buf, int64_t n) {
  auto* L = static_cast<Loop*>(h);
  int64_t accepted = 0;
  std::unique_lock<std::mutex> g(L->mu);
  L->cv_idle.wait(g, [&] { return L->inbox.empty() && !L->worker_busy; });
  grow_reserve(L->pending, static_cast<size_t>(n));
  for (int64_t k = 0; k < n; ++k) {
    Rec r;
    parse_rec(buf + k * kRecSize, &r);
    r.arrival = L->arrivals++;
    // malformed screen (VoteBatcher.build_phases' `ok` mask); height
    // and window screens run at stage() against last-synced state
    if (rec_malformed(L, r)) {
      ++L->rejected_malformed;
      continue;
    }
    L->pending.push_back(r);
    ++accepted;
  }
  return accepted;
}

// queue one wire buffer for the worker thread (copies the bytes: the
// caller's buffer is free the moment this returns).  The worker
// parses/screens while the tick thread drives verify/emit/device —
// the overlap that makes densify(k+1) concurrent with step(k).
int64_t ag_ing_push_async(void* h, const uint8_t* buf, int64_t n) {
  auto* L = static_cast<Loop*>(h);
  std::vector<uint8_t> copy(buf, buf + n * kRecSize);
  {
    std::lock_guard<std::mutex> g(L->mu);
    if (!L->worker.joinable())
      L->worker = std::thread(ingest_worker_main, L);
    L->inbox.push_back(std::move(copy));
    L->inbox_recs += n;
  }
  L->cv_in.notify_one();
  return n;
}

// wait until every queued async buffer has landed in pending — after
// this, stage() sees exactly the records a synchronous push would have
void ag_ing_flush(void* h) {
  auto* L = static_cast<Loop*>(h);
  std::unique_lock<std::mutex> lk(L->mu);
  L->cv_idle.wait(lk, [&] { return L->inbox.empty() && !L->worker_busy; });
}

// records queued/in-flight on the worker (observability + tests);
// counts a buffer until its records have landed in pending
int64_t ag_ing_async_depth(void* h) {
  auto* L = static_cast<Loop*>(h);
  std::lock_guard<std::mutex> g(L->mu);
  return L->inbox_recs;
}

// screen pending against the last-synced heights/window and snapshot
// the in-window lanes for verification; returns lane count.  Implies
// flush(): a stage must never run ahead of queued async pushes.
int64_t ag_ing_stage(void* h) {
  auto* L = static_cast<Loop*>(h);
  std::vector<Rec> work;
  {
    std::unique_lock<std::mutex> lk(L->mu);
    L->cv_idle.wait(lk,
                    [&] { return L->inbox.empty() && !L->worker_busy; });
    work.swap(L->pending);
  }
  grow_reserve(L->staged, work.size());
  for (const auto& r : work) {
    size_t i = static_cast<size_t>(r.instance);
    if (r.height != L->heights[i]) {
      ++L->dropped_stale_height;
    } else if (r.round >= agnes::sat_add(L->base_round[i], L->W)) {
      if (static_cast<int64_t>(L->held.size()) < L->held_cap)
        L->held.push_back(r);           // future: hold for rotation
      else
        ++L->dropped_held_overflow;     // cap: fail closed, count
    } else {
      L->staged.push_back(r);
    }
  }
  // hand pending's buffer back (hot per-tick path: keep steady-state
  // ticks allocation-free) unless the worker already refilled it
  work.clear();
  {
    std::lock_guard<std::mutex> g(L->mu);
    if (L->pending.empty() && work.capacity() > L->pending.capacity())
      L->pending.swap(work);
  }
  return static_cast<int64_t>(L->staged.size());
}

// verify inputs for the staged lanes: pub/sig bytes widened to i32 and
// the single padded SHA-512 block per lane (the exact layout
// bridge/ingest.py's _sha_blocks_np + vote_messages_np produce)
void ag_ing_fill_verify_inputs(void* h, int32_t* out_pub /* n*32 */,
                               int32_t* out_sig /* n*64 */,
                               uint32_t* out_blocks /* n*32 */) {
  auto* L = static_cast<Loop*>(h);
  uint8_t msg[45];
  uint8_t blk[128];
  for (size_t k = 0; k < L->staged.size(); ++k) {
    const Rec& r = L->staged[k];
    std::memset(msg, 0, sizeof(msg));
    msg[0] = static_cast<uint8_t>(r.typ);
    uint64_t hgt = static_cast<uint64_t>(r.height);
    for (int i = 0; i < 8; ++i) msg[1 + i] = (hgt >> (8 * i)) & 0xFF;
    uint32_t rnd = static_cast<uint32_t>(r.round);
    for (int i = 0; i < 4; ++i) msg[9 + i] = (rnd >> (8 * i)) & 0xFF;
    if (r.value == kNil) {
      std::memset(msg + 13, 0xFF, 32);  // NIL_WIRE = 2^256 - 1
    } else {
      uint64_t v = static_cast<uint64_t>(r.value);
      for (int i = 0; i < 8; ++i) msg[13 + i] = (v >> (8 * i)) & 0xFF;
    }
    const uint8_t* pk =
        L->pubkeys.empty() ? nullptr
                           : L->pubkeys.data() + r.validator * 32;
    std::memset(blk, 0, sizeof(blk));
    std::memcpy(blk, r.sig, 32);                    // R
    if (pk) std::memcpy(blk + 32, pk, 32);          // A
    std::memcpy(blk + 64, msg, 45);                 // M
    blk[109] = 0x80;
    blk[126] = (109 * 8) >> 8;
    blk[127] = (109 * 8) & 0xFF;
    for (int j = 0; j < 32; ++j) {
      out_blocks[k * 32 + j] =
          (uint32_t(blk[4 * j]) << 24) | (uint32_t(blk[4 * j + 1]) << 16) |
          (uint32_t(blk[4 * j + 2]) << 8) | uint32_t(blk[4 * j + 3]);
      if (pk) out_pub[k * 32 + j] = pk[j];
    }
    for (int j = 0; j < 64; ++j) out_sig[k * 64 + j] = r.sig[j];
  }
}

// ok = NULL means the unsigned path (only legal when the loop was
// created without pubkeys); verified lanes are retained for evidence
// and past-round lanes fall to the host tally
int64_t ag_ing_apply_verdicts(void* h, const uint8_t* ok) {
  auto* L = static_cast<Loop*>(h);
  if (ok == nullptr && L->require_verify) return -1;
  if (L->staged.empty()) return 0;

  // compact rejected lanes out IN PLACE, then move the whole block —
  // the log and the ready queue share it (no per-record copies)
  auto blk = std::make_shared<std::vector<Rec>>(std::move(L->staged));
  L->staged.clear();
  std::vector<Rec>& b = *blk;
  if (ok) {
    size_t w = 0;
    for (size_t k = 0; k < b.size(); ++k) {
      if (!ok[k]) {
        ++L->rejected_signature;
        continue;
      }
      if (w != k) b[w] = b[k];
      ++w;
    }
    b.resize(w);
  }

  // rotated-out rounds fall to the host tally; when none exist (the
  // common case) the block rides to emit untouched
  bool any_past = false;
  for (const Rec& r : b)
    if (r.round < L->base_round[static_cast<size_t>(r.instance)]) {
      any_past = true;
      break;
    }
  int64_t kept;
  if (!any_past) {
    kept = static_cast<int64_t>(b.size());
    L->log.push_back(blk);
    if (!b.empty()) L->ready.push_back(blk);
  } else {
    auto cur = std::make_shared<std::vector<Rec>>();
    cur->reserve(b.size());
    for (const Rec& r : b) {
      if (r.round < L->base_round[static_cast<size_t>(r.instance)])
        host_tally_add(L, r);
      else
        cur->push_back(r);
    }
    kept = static_cast<int64_t>(cur->size());
    L->log.push_back(blk);              // evidence keeps ALL verified
    if (!cur->empty()) L->ready.push_back(std::move(cur));
  }
  return kept;
}

namespace {

// scatter one vote into a phase; routes slot-overflow to the host tally
inline void scatter_vote(Loop* L, Phase& ph, const Rec& r) {
  int32_t s = kVotedNil;
  if (r.value != kNil) {
    s = slot_for(L, r.instance, r.value);
    if (s == kVotedNil - 1) {           // slot budget overflow ->
      ++L->overflow_votes;              // host tally keeps the vote
      host_tally_add(L, r);
      return;
    }
  }
  size_t cell = static_cast<size_t>(r.instance * L->V + r.validator);
  ph.slots[cell] = s;
  ph.mask[cell] = 1;
  ++ph.n_votes;
}

// intern every new (instance, value) pair in ascending order — the
// exact allocation order VoteBatcher._intern_slots uses, so slot
// numbering matches the numpy path bit-for-bit
void intern_ascending(Loop* L, std::vector<std::pair<int64_t, int64_t>>& pairs) {
  std::sort(pairs.begin(), pairs.end());
  pairs.erase(std::unique(pairs.begin(), pairs.end()), pairs.end());
  for (auto& pv : pairs) slot_for(L, pv.first, pv.second);
}

}  // namespace

// dedup + layer + intern + scatter the ready lanes into the NEXT emit
// buffer set (double buffer: pointers from the previous emit stay
// valid while the device consumes them).  Returns the phase count.
int64_t ag_ing_emit(void* h) {
  auto* L = static_cast<Loop*>(h);
  L->cur ^= 1;
  EmitSet& set = L->sets[L->cur];
  set.used = 0;
  if (L->ready.empty()) return 0;

  std::vector<Loop::Block> blocks;
  blocks.swap(L->ready);

  // --- fast path: one round, each class's cells occupied at most
  // once — the honest gossip ticks (one phase, or both classes of a
  // round pushed into one build for a single 2n-lane verify; mirrors
  // VoteBatcher.build_phases).  Epoch-stamped scans, no sort; the
  // stamp array is per (class, cell) so the classes don't collide.
  if (L->cell_epoch.size() <
      static_cast<size_t>(2 * L->I * L->V))
    L->cell_epoch.assign(static_cast<size_t>(2 * L->I * L->V), 0);
  ++L->epoch;
  bool fast = true;
  bool have_typ[2] = {false, false};
  const Rec& first = (*blocks[0])[0];
  std::vector<std::pair<int64_t, int64_t>> pairs;
  for (const auto& blk : blocks) {
    for (const Rec& r : *blk) {
      if (r.round != first.round || r.typ < 0 || r.typ > 1) {
        fast = false;
        break;
      }
      size_t cell = static_cast<size_t>(
          (r.typ * L->I + r.instance) * L->V + r.validator);
      if (L->cell_epoch[cell] == L->epoch) { fast = false; break; }
      L->cell_epoch[cell] = L->epoch;
      have_typ[r.typ] = true;
      if (r.value != kNil &&
          slot_lookup(L, r.instance, r.value) == kVotedNil)
        pairs.emplace_back(r.instance, r.value);
    }
    if (!fast) break;
  }
  if (fast) {
    intern_ascending(L, pairs);
    // classes emit in (prevote, precommit) order — the general path's
    // sort order, and the order consensus expects to make progress
    for (int t = 0; t <= 1; ++t) {
      if (!have_typ[t]) continue;
      Phase& ph = set.acquire(L->I * L->V);
      ph.round = static_cast<int32_t>(first.round);
      ph.typ = static_cast<int32_t>(t);
      for (const auto& blk : blocks)
        for (const Rec& r : *blk)
          if (r.typ == t) scatter_vote(L, ph, r);
      if (ph.n_votes == 0) --set.used;   // all lanes spilled: drop it
    }
    return static_cast<int64_t>(set.used);
  }

  // --- general path: flatten to pointers, then ONE index sort orders
  // everything (VoteBatcher's lexsort): phase groups, duplicates and
  // layers fall out of adjacency.  Pointers avoid shuffling the
  // ~120-byte records.
  std::vector<const Rec*> b;
  for (const auto& blk : blocks)
    for (const Rec& r : *blk) b.push_back(&r);
  std::vector<uint32_t> idx(b.size());
  for (size_t k = 0; k < b.size(); ++k) idx[k] = static_cast<uint32_t>(k);
  std::sort(idx.begin(), idx.end(), [&b](uint32_t x, uint32_t y) {
    const Rec& a = *b[x];
    const Rec& c = *b[y];
    if (a.round != c.round) return a.round < c.round;
    if (a.typ != c.typ) return a.typ < c.typ;
    if (a.instance != c.instance) return a.instance < c.instance;
    if (a.validator != c.validator) return a.validator < c.validator;
    if (a.value != c.value) return a.value < c.value;
    return a.arrival < c.arrival;
  });

  // drop exact duplicates (same cell, same value)
  std::vector<uint32_t> keep;
  keep.reserve(idx.size());
  for (uint32_t k : idx) {
    if (!keep.empty()) {
      const Rec& q = *b[keep.back()];
      const Rec& r = *b[k];
      if (q.round == r.round && q.typ == r.typ &&
          q.instance == r.instance && q.validator == r.validator &&
          q.value == r.value)
        continue;
    }
    keep.push_back(k);
  }

  // layer = rank within the (round, typ, instance, validator) run
  std::vector<int32_t> layer(keep.size(), 0);
  for (size_t k = 1; k < keep.size(); ++k) {
    const Rec& q = *b[keep[k - 1]];
    const Rec& r = *b[keep[k]];
    if (q.round == r.round && q.typ == r.typ &&
        q.instance == r.instance && q.validator == r.validator)
      layer[k] = layer[k - 1] + 1;
  }

  // intern slots in ascending (instance, value) order (SlotMap parity)
  pairs.clear();
  for (uint32_t k : keep)
    if (b[k]->value != kNil)
      pairs.emplace_back(b[k]->instance, b[k]->value);
  intern_ascending(L, pairs);

  // group by (round, typ, layer) ascending — already the sort order
  // except layer, so bucket by key into an ordered map
  std::map<std::tuple<int64_t, int64_t, int32_t>, size_t> groups;
  std::vector<std::vector<uint32_t>> members;
  for (size_t k = 0; k < keep.size(); ++k) {
    auto key = std::make_tuple(b[keep[k]]->round, b[keep[k]]->typ,
                               layer[k]);
    auto it = groups.find(key);
    if (it == groups.end()) {
      it = groups.emplace(key, members.size()).first;
      members.emplace_back();
    }
    members[it->second].push_back(keep[k]);
  }

  for (auto& kv : groups) {
    Phase& ph = set.acquire(L->I * L->V);
    ph.round = static_cast<int32_t>(std::get<0>(kv.first));
    ph.typ = static_cast<int32_t>(std::get<1>(kv.first));
    for (uint32_t k : members[kv.second]) scatter_vote(L, ph, *b[k]);
    if (ph.n_votes == 0) --set.used;    // all lanes overflowed to host
  }
  return static_cast<int64_t>(set.used);
}

// pointers into the current emit set; valid until the emit after next
int64_t ag_ing_phase(void* h, int64_t k, int32_t* out_round,
                     int32_t* out_typ, int64_t* out_n,
                     const int32_t** out_slots,
                     const uint8_t** out_mask) {
  auto* L = static_cast<Loop*>(h);
  EmitSet& set = L->sets[L->cur];
  if (k < 0 || k >= static_cast<int64_t>(set.used)) return -1;
  const Phase& ph = set.phases[static_cast<size_t>(k)];
  *out_round = ph.round;
  *out_typ = ph.typ;
  *out_n = ph.n_votes;
  *out_slots = ph.slots.data();
  *out_mask = ph.mask.data();
  return 0;
}

// [(instance, height, round, value)] late precommit-value quorums
int64_t ag_ing_drain_events(void* h, int64_t* out, int64_t cap) {
  auto* L = static_cast<Loop*>(h);
  int64_t n = 0;
  for (auto& e : L->events) {
    if (n >= cap) break;
    for (int j = 0; j < 4; ++j) out[4 * n + j] = e[static_cast<size_t>(j)];
    ++n;
  }
  L->events.erase(L->events.begin(), L->events.begin() + n);
  return n;
}

int64_t ag_ing_decode_slot(void* h, int64_t instance, int32_t slot) {
  auto* L = static_cast<Loop*>(h);
  if (instance < 0 || instance >= L->I || slot < 0 ||
      slot >= L->slot_count[static_cast<size_t>(instance)])
    return agnes::kNoValue;
  return L->slot_vals[static_cast<size_t>(instance * L->S + slot)];
}

// two conflicting signed votes by `validator` in `instance` with the
// same (height, round, typ) and different values -> 2 wire records
int64_t ag_ing_evidence(void* h, int64_t instance, int64_t validator,
                        uint8_t* out /* 2 * 96 bytes */) {
  auto* L = static_cast<Loop*>(h);
  // the log is block-shared with the verdict stage; flatten the
  // candidate votes first (one validator's votes: a short list)
  std::vector<const Rec*> cand;
  for (const auto& blk : L->log)
    for (const Rec& r : *blk)
      if (r.instance == instance && r.validator == validator)
        cand.push_back(&r);
  for (size_t a = 0; a < cand.size(); ++a) {
    const Rec& x = *cand[a];
    for (size_t bidx = a + 1; bidx < cand.size(); ++bidx) {
      const Rec& y = *cand[bidx];
      if (x.height == y.height && x.round == y.round && x.typ == y.typ &&
          x.value != y.value) {
        pack_rec(x, out);
        pack_rec(y, out + kRecSize);
        return 1;
      }
    }
  }
  return 0;
}

void ag_ing_clear_log(void* h) { static_cast<Loop*>(h)->log.clear(); }

// --- snapshot surface (utils/checkpoint.py save/load_native_loop) ----------
// The durable state a crash must not lose: slot interning (decision
// decode), the verified-vote log (slashing evidence), counters, and
// the window (restored via ag_ing_sync by the caller).  In-flight
// votes (pending/staged/held) and host tallies are NOT exported —
// a restarted node re-receives them from peers (save_executor's
// crash-recovery story).

// dump slot values as [I*S] (kNoValue where unallocated)
void ag_ing_export_slots(void* h, int64_t* out) {
  auto* L = static_cast<Loop*>(h);
  std::memcpy(out, L->slot_vals.data(),
              sizeof(int64_t) * static_cast<size_t>(L->I * L->S));
}

// restore slot values (counts derived from the kNoValue sentinel);
// slots are allocated densely, so the first sentinel ends the row
void ag_ing_import_slots(void* h, const int64_t* vals) {
  auto* L = static_cast<Loop*>(h);
  L->slot_vals.assign(vals, vals + L->I * L->S);
  for (int64_t i = 0; i < L->I; ++i) {
    int32_t n = 0;
    while (n < L->S && vals[i * L->S + n] != agnes::kNoValue) ++n;
    L->slot_count[static_cast<size_t>(i)] = n;
  }
}

int64_t ag_ing_log_size(void* h) {
  auto* L = static_cast<Loop*>(h);
  int64_t n = 0;
  for (const auto& blk : L->log) n += static_cast<int64_t>(blk->size());
  return n;
}

// dump the verified-vote log as packed wire records (the same 96-byte
// layout ag_ing_push consumes)
void ag_ing_export_log(void* h, uint8_t* out) {
  auto* L = static_cast<Loop*>(h);
  for (const auto& blk : L->log)
    for (const Rec& r : *blk) {
      pack_rec(r, out);
      out += kRecSize;
    }
}

// restore the log from packed wire records.  These lanes were
// verified before the snapshot, but the snapshot itself is untrusted
// input to this raw ABI: the same malformed screen as push applies —
// a corrupted file must not inject records push would reject into
// the slashing-evidence log.  ALL-OR-NOTHING: records are screened
// while parsing into a LOCAL staging block, and a corrupt snapshot
// (nonzero return) commits nothing — a partial evidence log
// masquerading as a successful restore would be worse than failing.
// FRESH-ONLY: the import targets a freshly constructed loop; merging a
// snapshot's log into live evidence would duplicate records and skew
// every log counter, so a non-empty log is rejected outright (-1).
int64_t ag_ing_import_log(void* h, const uint8_t* buf, int64_t n) {
  auto* L = static_cast<Loop*>(h);
  if (!L->log.empty()) return -1;     // refuse to merge with live state
  auto blk = std::make_shared<std::vector<Rec>>();
  blk->reserve(static_cast<size_t>(n));
  int64_t dropped = 0;
  for (int64_t k = 0; k < n; ++k) {
    Rec r;
    parse_rec(buf + k * kRecSize, &r);
    if (rec_malformed(L, r))
      ++dropped;
    else
      blk->push_back(r);
  }
  if (dropped) return dropped;        // blk is local: nothing committed
  for (Rec& r : *blk) r.arrival = L->arrivals++;
  if (!blk->empty()) L->log.push_back(std::move(blk));
  return 0;
}

// restore counters: [malformed, stale_height, signature, overflow,
// held_overflow] (held size and log size are structural, not set)
void ag_ing_restore_counters(void* h, const int64_t* in) {
  auto* L = static_cast<Loop*>(h);
  L->rejected_malformed = in[0];
  L->dropped_stale_height = in[1];
  L->rejected_signature = in[2];
  L->overflow_votes = in[3];
  L->dropped_held_overflow = in[4];
}

// counters: [malformed, stale_height, signature, overflow, held, log,
//            held_overflow]
void ag_ing_counters(void* h, int64_t* out) {
  auto* L = static_cast<Loop*>(h);
  // rejected_malformed is worker-shared; the rest are tick-only (the
  // one lock covers the lot — this is a cold observability path)
  std::lock_guard<std::mutex> g(L->mu);
  out[0] = L->rejected_malformed;
  out[1] = L->dropped_stale_height;
  out[2] = L->rejected_signature;
  out[3] = L->overflow_votes;
  out[4] = static_cast<int64_t>(L->held.size());
  int64_t logged = 0;
  for (const auto& blk : L->log)
    logged += static_cast<int64_t>(blk->size());
  out[5] = logged;
  out[6] = L->dropped_held_overflow;
}

}  // extern "C"
