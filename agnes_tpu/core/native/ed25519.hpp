// Ed25519 (RFC 8032) for the native host core: signing for the
// executor's own votes/proposals (the reference stubs "sign the vote",
// consensus_executor.rs:35-41) and verification as the host fallback /
// oracle for the JAX batch verifier.  Written from the RFC: radix-2^51
// field arithmetic on unsigned __int128 products, extended-coordinate
// points, variable-time scalar multiplication (verification handles
// public data only; signing uses only the caller-supplied seed and is
// not hardened against timing side channels — fixture/driver use).
#pragma once

#include <cstdint>

namespace agnes {

// public_key[32] out of seed[32]
void ed25519_pubkey(const uint8_t seed[32], uint8_t out_pk[32]);

// signature[64] = R || S over msg
void ed25519_sign(const uint8_t seed[32], const uint8_t* msg, uint64_t n,
                  uint8_t out_sig[64]);

// full RFC 8032 §5.1.7 verification (canonical A/R, S < L, group eq)
bool ed25519_verify(const uint8_t pk[32], const uint8_t* msg, uint64_t n,
                    const uint8_t sig[64]);

}  // namespace agnes
