// Native admission front-end: the serve plane's per-record hot path
// in C++ (ISSUE 14 — "fuse the C++ ingest loop into the serve plane").
//
// The Python AdmissionQueue (serve/queue.py) pays the GIL per record:
// wire parse, the malformed/fairness/capacity screens, and a Python
// SHA-256 loop for the dedup cache all run on the submit thread.  This
// module is its byte-compatible C++ twin, reached through the audited
// ctypes wrapper serve/native_admission.py — one GIL-releasing call
// per submit and per drain, everything per-record behind it native:
//
//   ag_adm_submit    parse + instance-range screen + per-instance
//                    fairness (occupancy + rank-within-submit < cap) +
//                    overload policy (reject-newest / drop-oldest) +
//                    SHA-256 digest of each ADMITTED record (the
//                    VerifiedCache key; sha512.cpp grew the SHA-256
//                    schedule), all under one internal mutex
//   ag_adm_drain     pop the n oldest records and densify them to the
//                    WireColumns arrays VoteBatcher.add_arrays takes —
//                    the Python/JAX side only plans the ladder rung
//                    and dispatches
//   ag_adm_bls_screen  the BLS class-bucket HEADER screens (range /
//                    PoP / quarantine) for BlsClassTable.fold; the
//                    on-curve share decode stays with the oracle
//
// ISSUE 20 split the queue internals into admission.hpp so the shard
// group (admission_shards.cpp) and the zero-copy densify drain
// (admission_phases.cpp) share the exact submit/drain arithmetic; the
// single-queue C ABI lives here unchanged.
//
// Semantics are a LEAF-FOR-LEAF port of AdmissionQueue.submit/drain
// (reject taxonomy, counter names and ordering, eviction math, digest
// bytes) — the admission model checker (PR 7) specifies the behavior,
// and tests/test_native_admission.py replays its corpus through both
// implementations.  Where this file and serve/queue.py could disagree,
// serve/queue.py is the specification.
//
// Thread safety: ONE mutex guards the whole handle.  submit and drain
// may race (the threaded host's submit vs dispatch threads) — this is
// what lets ThreadedVoteService drop the Python admission lock around
// a native queue, keeping the GIL-release span lock-free (the LOCK005
// rule in analysis/lockcheck.py polices the inverse nesting).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <deque>
#include <limits>
#include <mutex>
#include <vector>

#include "admission.hpp"
#include "sha512.hpp"

namespace agnes_adm {

void pop_front(AdmQ* A, int64_t n) {
  for (int64_t k = 0; k < n; ++k) {
    A->inst_counts[static_cast<size_t>(rec_instance(A->q.front().raw))]--;
    A->q.pop_front();
  }
}

int64_t submit_records(AdmQ* A, const uint8_t* buf,
                       const int64_t* rec_idx, int64_t n_rec,
                       int64_t tail_malformed, int64_t seq,
                       int64_t* out_counts, uint8_t* out_digests,
                       uint8_t* out_kept) {
  std::lock_guard<std::mutex> g(A->mu);
  if (seq < 0) seq = ++A->next_seq;
  A->counters[0] += n_rec + tail_malformed;
  int64_t malformed = tail_malformed;
  if (out_kept) std::memset(out_kept, 0, static_cast<size_t>(n_rec));
  if (n_rec == 0) {
    A->counters[4] += malformed;
    out_counts[0] = 0; out_counts[1] = 0; out_counts[2] = 0;
    out_counts[3] = malformed; out_counts[4] = 0;
    return seq;
  }

  // instance-range screen + fairness: occupancy-so-far + rank within
  // this submit < cap (the rank counts every malformed-surviving
  // record of the instance, matching queue._cumcount over inst_k)
  ++A->epoch;
  std::vector<int64_t> keep;   // positions into rec_idx, ascending
  keep.reserve(static_cast<size_t>(n_rec));
  int64_t rejected_fairness = 0;
  for (int64_t j = 0; j < n_rec; ++j) {
    const int64_t k = rec_idx ? rec_idx[j] : j;
    const int64_t inst = rec_instance(buf + k * kRecSize);
    if (inst >= A->I) {
      ++malformed;
      continue;
    }
    const size_t i = static_cast<size_t>(inst);
    if (A->seen_epoch[i] != A->epoch) {
      A->seen_epoch[i] = A->epoch;
      A->seen[i] = 0;
    }
    const int64_t occ = A->inst_counts[i] + A->seen[i]++;
    if (occ >= A->instance_cap)
      ++rejected_fairness;
    else
      keep.push_back(j);
  }

  // capacity / overload policy (the exact queue.submit arithmetic)
  int64_t rejected_overflow = 0;
  int64_t evicted = 0;
  const int64_t depth = static_cast<int64_t>(A->q.size());
  const int64_t room = A->capacity - depth;
  if (static_cast<int64_t>(keep.size()) > room) {
    if (A->policy == 0) {                       // reject-newest
      const int64_t hold = room > 0 ? room : 0;
      rejected_overflow = static_cast<int64_t>(keep.size()) - hold;
      keep.resize(static_cast<size_t>(hold));
    } else {                                    // drop-oldest
      if (static_cast<int64_t>(keep.size()) > A->capacity) {
        rejected_overflow =
            static_cast<int64_t>(keep.size()) - A->capacity;
        keep.erase(keep.begin(),
                   keep.end() - static_cast<size_t>(A->capacity));
      }
      const int64_t over =
          static_cast<int64_t>(keep.size()) - (A->capacity - depth);
      evicted = depth < over ? depth : over;
      if (evicted > 0) {
        pop_front(A, evicted);                  // never counts drained
        A->counters[5] += evicted;
      }
    }
  }

  // enqueue at the sorted (seq, sub_idx) position: a plain push_back
  // in the single-queue / unraced case, a mid-deque splice only when
  // the shard group's atomic handed a racing submit a smaller seq
  // after a larger one already landed here (see admission.hpp)
  const int64_t accepted = static_cast<int64_t>(keep.size());
  auto ins = A->q.end();
  if (!A->q.empty() && A->q.back().seq > seq)
    ins = std::upper_bound(
        A->q.begin(), A->q.end(), seq,
        [](int64_t s, const NRec& r) { return s < r.seq; });
  for (size_t j = 0; j < keep.size(); ++j) {
    const int64_t k = rec_idx ? rec_idx[keep[j]] : keep[j];
    NRec r;
    std::memcpy(r.raw, buf + k * kRecSize, kRecSize);
    if (A->digests) {
      // digest of the RAW record bytes — the "these exact bytes were
      // device-verified" key (queue._record_digests)
      agnes::sha256(r.raw, kRecSize, r.digest);
      if (out_digests)
        std::memcpy(out_digests + 32 * j, r.digest, 32);
    } else {
      std::memset(r.digest, 0, 32);
    }
    // NaN until ag_adm_set_chunk_ts stamps it: a concurrent drain
    // popping the record in that gap must be able to TELL it is
    // unstamped (the wrapper substitutes its own clock) — a 0.0
    // sentinel would read as epoch-scale admission wait and pin the
    // latency histograms' p99 at hours
    r.ts = std::numeric_limits<double>::quiet_NaN();
    r.seq = seq;
    r.sub_idx = k;
    r.verified = 0;
    ins = A->q.insert(ins, r);
    ++ins;
    A->inst_counts[static_cast<size_t>(rec_instance(r.raw))]++;
    if (out_kept) out_kept[keep[j]] = 1;
  }

  A->counters[1] += accepted;
  A->counters[2] += rejected_overflow;
  A->counters[3] += rejected_fairness;
  A->counters[4] += malformed;
  out_counts[0] = accepted;
  out_counts[1] = rejected_overflow;
  out_counts[2] = rejected_fairness;
  out_counts[3] = malformed;
  out_counts[4] = evicted;
  return seq;
}

void set_chunk_ts_core(AdmQ* A, int64_t seq, double ts) {
  std::lock_guard<std::mutex> g(A->mu);
  for (auto it = A->q.rbegin(); it != A->q.rend(); ++it) {
    if (it->seq > seq) continue;
    if (it->seq < seq) break;
    it->ts = ts;
  }
}

void mark_verified_core(AdmQ* A, int64_t seq, const uint8_t* ver,
                        int64_t n) {
  std::lock_guard<std::mutex> g(A->mu);
  int64_t j = n - 1;
  for (auto it = A->q.rbegin(); it != A->q.rend() && j >= 0; ++it) {
    if (it->seq > seq) continue;      // a later submit's records
    if (it->seq < seq) break;         // past the target (FIFO order)
    it->verified = ver[j--] ? 1 : 0;
  }
}

double min_stamped_ts(AdmQ* A) {
  std::lock_guard<std::mutex> g(A->mu);
  double best = std::numeric_limits<double>::quiet_NaN();
  for (const NRec& r : A->q)
    if (!std::isnan(r.ts) && (std::isnan(best) || r.ts < best))
      best = r.ts;
  return best;
}

}  // namespace agnes_adm

using namespace agnes_adm;

extern "C" {

void* ag_adm_new(int64_t I, int64_t capacity, int64_t instance_cap,
                 int32_t policy, int32_t with_digests) {
  // raw C ABI: hostile dimensions fail closed (NULL), never throw
  // across the boundary (the ag_ing_new contract)
  if (I <= 0 || I > (int64_t{1} << 31) || capacity <= 0 ||
      instance_cap <= 0 || (policy != 0 && policy != 1))
    return nullptr;
  try {
    auto* A = new AdmQ();
    A->I = I;
    A->capacity = capacity;
    A->instance_cap = instance_cap;
    A->policy = policy;
    A->digests = with_digests != 0;
    A->inst_counts.assign(static_cast<size_t>(I), 0);
    A->seen.assign(static_cast<size_t>(I), 0);
    A->seen_epoch.assign(static_cast<size_t>(I), 0);
    return A;
  } catch (...) {
    return nullptr;
  }
}

void ag_adm_free(void* h) { delete static_cast<AdmQ*>(h); }

// The admission hot path: one call per submit, GIL released by ctypes
// for its whole span.  out_counts = [accepted, rejected_overflow,
// rejected_fairness, rejected_malformed, evicted]; out_digests (may be
// NULL, else sized n_whole*32) receives the SHA-256 of each ADMITTED
// record in admission order — the wrapper looks them up in the Python
// VerifiedCache and marks hits back via ag_adm_mark_verified.
// Returns the submit's seq id.
int64_t ag_adm_submit(void* h, const uint8_t* buf, int64_t nbytes,
                      int64_t* out_counts, uint8_t* out_digests) {
  auto* A = static_cast<AdmQ*>(h);
  const int64_t n_whole = nbytes / kRecSize;
  const int64_t tail = (nbytes % kRecSize) ? 1 : 0;
  return submit_records(A, buf, nullptr, n_whole, tail, -1, out_counts,
                        out_digests, nullptr);
}

// stamp submit `seq`'s accepted records with their admission instant.
// A separate call (not a submit argument) so the wrapper can keep the
// Python queue's EXACT clock discipline — AdmissionQueue reads its
// clock once per submit and only when records were accepted, and
// fake-clock differentials count invocations.  Same back-walk as
// mark_verified; a record drained before its stamp carries NaN, which
// the wrapper's drain replaces with its own clock (only reachable
// under a concurrent drain).
void ag_adm_set_chunk_ts(void* h, int64_t seq, double ts) {
  set_chunk_ts_core(static_cast<AdmQ*>(h), seq, ts);
}

// flag submit `seq`'s accepted records as dedup-cache hits.  `ver` is
// the cache's [n] hit mask in admission order; records of the submit
// already drained (a concurrent dispatch-thread drain between the
// submit and this call) are skipped — they dispatch signed, which is
// always the safe direction.  Walks from the back so partial front
// drains keep the alignment: the LAST record of the submit pairs with
// ver[n-1].
void ag_adm_mark_verified(void* h, int64_t seq, const uint8_t* ver,
                          int64_t n) {
  mark_verified_core(static_cast<AdmQ*>(h), seq, ver, n);
}

int64_t ag_adm_depth(void* h) {
  auto* A = static_cast<AdmQ*>(h);
  std::lock_guard<std::mutex> g(A->mu);
  return static_cast<int64_t>(A->q.size());
}

int64_t ag_adm_instance_depth(void* h, int64_t i) {
  auto* A = static_cast<AdmQ*>(h);
  std::lock_guard<std::mutex> g(A->mu);
  if (i < 0 || i >= A->I) return 0;
  return A->inst_counts[static_cast<size_t>(i)];
}

// admission instant of the oldest STAMPED record; NaN when empty or
// when nothing queued is stamped yet.  ISSUE 20 fix: the front record
// can transiently carry the NaN sentinel while deeper records are
// stamped (submit enqueues, THEN stamps; a racing drain can observe
// the gap), and the old front-only read handed that NaN to
// MicroBatcher's deadline close.  A guarded min over the live records
// can never surface a transient NaN while stamped work is waiting.
double ag_adm_oldest_ts(void* h) {
  return min_stamped_ts(static_cast<AdmQ*>(h));
}

void ag_adm_counters(void* h, int64_t* out7) {
  auto* A = static_cast<AdmQ*>(h);
  std::lock_guard<std::mutex> g(A->mu);
  std::memcpy(out7, A->counters, sizeof(A->counters));
}

// fold a foreign admission outcome into the shared taxonomy —
// submit_bls maps the class-table's reject causes onto these counters
// exactly like the Python queue does.  deltas = [submitted, admitted,
// rejected_overflow, rejected_fairness, rejected_malformed].
void ag_adm_add_counters(void* h, const int64_t* deltas5) {
  auto* A = static_cast<AdmQ*>(h);
  std::lock_guard<std::mutex> g(A->mu);
  for (int k = 0; k < 5; ++k) A->counters[k] += deltas5[k];
}

// drain-and-densify: pop the n oldest records (n <= depth, caller
// sized) straight into the WireColumns arrays — parse semantics are
// unpack_wire_votes' exactly (value rides UNCLAMPED when the nil flag
// is clear; deeper screens stay with the batcher).  out_dig may be
// NULL (dedup off).  Counts `drained`; returns n.
int64_t ag_adm_drain(void* h, int64_t n, int64_t* inst, int64_t* val,
                     int64_t* hts, int64_t* rnd, int64_t* typ,
                     int64_t* value, uint8_t* sigs, uint8_t* ver,
                     uint8_t* out_dig, double* ts) {
  auto* A = static_cast<AdmQ*>(h);
  std::lock_guard<std::mutex> g(A->mu);
  if (n < 0) n = 0;   // hostile caller: never count drained backwards
  if (n > static_cast<int64_t>(A->q.size()))
    n = static_cast<int64_t>(A->q.size());
  for (int64_t k = 0; k < n; ++k) {
    const NRec& r = A->q.front();
    parse_record(r, k, inst, val, hts, rnd, typ, value, sigs, ver,
                 out_dig, ts);
    A->inst_counts[static_cast<size_t>(rec_instance(r.raw))]--;
    A->q.pop_front();
  }
  A->counters[6] += n;
  return n;
}

// FIFO dump of the queued records (raw bytes + verified flags) for the
// model checker's canonical-form differential; writes at most `cap`
// records (the caller sized its buffers from a depth read made OUTSIDE
// this mutex — a concurrent submit may have grown the queue since, and
// an unbounded write would run off the end of those buffers).  Returns
// the count written.
int64_t ag_adm_export(void* h, uint8_t* raw, uint8_t* ver,
                      int64_t cap) {
  auto* A = static_cast<AdmQ*>(h);
  std::lock_guard<std::mutex> g(A->mu);
  int64_t k = 0;
  for (const NRec& r : A->q) {
    if (k >= cap) break;
    std::memcpy(raw + k * kRecSize, r.raw, kRecSize);
    ver[k] = r.verified;
    ++k;
  }
  return k;
}

// BLS class-bucket HEADER screens (BlsClassTable.fold pass 1, minus
// the on-curve decode): per record the FIRST failing screen wins, in
// the fold's order — range (instance/typ) -> unknown validator -> PoP
// missing -> quarantined.  pop_ok/quarantined are the registry's [V]
// masks.  Stateless; codes: 0 ok, 1 malformed, 2 unknown_validator,
// 3 pop_missing, 4 quarantined.  Returns the whole-record count.
int64_t ag_adm_bls_screen(const uint8_t* buf, int64_t nbytes, int64_t I,
                          int64_t V, const uint8_t* pop_ok,
                          const uint8_t* quarantined,
                          uint8_t* out_code) {
  const int64_t n = nbytes / kBlsRecSize;
  for (int64_t k = 0; k < n; ++k) {
    const uint8_t* p = buf + k * kBlsRecSize;
    uint32_t u32;
    std::memcpy(&u32, p + 0, 4);
    const int64_t inst = u32;
    std::memcpy(&u32, p + 4, 4);
    const int64_t v = u32;
    const uint8_t typ = p[20];
    if (inst >= I || typ > 1)
      out_code[k] = 1;
    else if (v >= V)
      out_code[k] = 2;
    else if (!pop_ok[v])
      out_code[k] = 3;
    else if (quarantined[v])
      out_code[k] = 4;
    else
      out_code[k] = 0;
  }
  return n;
}

}  // extern "C"
