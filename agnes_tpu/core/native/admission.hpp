// Shared internals of the native admission plane (ISSUE 20).
//
// PR 14 kept the whole admission queue inside one translation unit;
// the sharded front-end (admission_shards.cpp) and the zero-copy
// densify drain (admission_phases.cpp) need the same record/queue
// structures and the exact submit/drain arithmetic, so the core moved
// here.  admission.cpp remains the single-queue C ABI; this header is
// internal to core/native and is NOT part of the C ABI surface.
//
// Ordering contract (new with sharding): every AdmQ deque is sorted by
// (seq, sub_idx).  A single queue gets this for free — seq allocation
// and push share the handle mutex — but the shard group allocates seq
// from a group-level atomic OUTSIDE any shard mutex, so two racing
// submits can reach the same shard out of seq order.  submit_records
// therefore inserts at the sorted position (a no-op push_back in the
// common monotone case).  The sorted deque is what makes the group's
// k-way merge drain a faithful replay of the single-queue stream, and
// is what the back-walking set_chunk_ts / mark_verified cores rely on
// to stop early.

#pragma once

#include <cstdint>
#include <cstring>
#include <deque>
#include <limits>
#include <mutex>
#include <vector>

namespace agnes_adm {

constexpr int kRecSize = 96;       // the packed Ed25519 wire record
constexpr int kBlsRecSize = 224;   // 32B header + 192B G2 share

struct NRec {
  uint8_t raw[kRecSize];
  uint8_t digest[32];
  double ts;                       // admission instant (caller clock)
  int64_t seq;                     // submit id (mark_verified target)
  int64_t sub_idx;                 // record index within its submit —
                                   // (seq, sub_idx) is the global
                                   // arrival order across shards
  uint8_t verified;                // dedup-cache pre-verified flag
};

struct AdmQ {
  int64_t I, capacity, instance_cap;
  int32_t policy;                  // 0 reject_newest, 1 drop_oldest
  bool digests;                    // hash admitted records (cache on)

  std::mutex mu;
  std::deque<NRec> q;              // sorted by (seq, sub_idx)
  std::vector<int64_t> inst_counts;   // [I] queue occupancy
  // per-submit rank scratch, epoch-stamped so a submit never pays an
  // O(I) clear (the ingest.cpp cell_epoch idiom)
  std::vector<int64_t> seen;
  std::vector<uint64_t> seen_epoch;
  uint64_t epoch = 0;
  int64_t next_seq = 0;

  // counters, AdmissionQueue.counters order:
  // [submitted, admitted, rejected_overflow, rejected_fairness,
  //  rejected_malformed, evicted, drained]
  int64_t counters[7] = {0, 0, 0, 0, 0, 0, 0};
};

inline int64_t rec_instance(const uint8_t* p) {
  uint32_t u32;
  std::memcpy(&u32, p, 4);
  return static_cast<int64_t>(u32);
}

// pop the n oldest records (n <= q.size()), updating occupancy; the
// Python _pop's count_drained flag is the caller's job.  Caller holds
// A->mu.
void pop_front(AdmQ* A, int64_t n);

// The admission screens + enqueue over a SELECTION of a wire buffer:
// rec_idx[n_rec] are the whole-record indices this queue owns (NULL
// means the identity 0..n_rec-1 — the single-queue path).  Locks
// A->mu for its whole span.  `tail_malformed` seeds the malformed
// count (the buffer's trailing partial record, charged to the routing
// shard).  `seq` < 0 allocates ++A->next_seq under the mutex (single
// queue); >= 0 uses the caller's id (the shard group's atomic).
//
// out_counts = [accepted, rejected_overflow, rejected_fairness,
// rejected_malformed, evicted].  out_digests (may be NULL) receives
// the SHA-256 of each ADMITTED record, compact in THIS queue's
// admission order.  out_kept (may be NULL, else sized n_rec) gets a
// 0/1 admitted flag per rec_idx position so a fan-in caller can
// gather digests back into global admission order.  Returns seq.
int64_t submit_records(AdmQ* A, const uint8_t* buf,
                       const int64_t* rec_idx, int64_t n_rec,
                       int64_t tail_malformed, int64_t seq,
                       int64_t* out_counts, uint8_t* out_digests,
                       uint8_t* out_kept);

// back-walking cores of ag_adm_set_chunk_ts / ag_adm_mark_verified;
// each locks A->mu.  `ver` is the verified mask over THIS queue's
// records of submit `seq`, in its admission order.
void set_chunk_ts_core(AdmQ* A, int64_t seq, double ts);
void mark_verified_core(AdmQ* A, int64_t seq, const uint8_t* ver,
                        int64_t n);

// guarded oldest-timestamp scan: the front record can still carry the
// NaN "unstamped" sentinel while deeper records are stamped (submit
// stamps AFTER enqueue, and a racing drain may interleave), so the
// deadline closer needs the min over the STAMPED records, not the
// front.  Returns NaN only when no queued record is stamped.  Locks
// A->mu.
double min_stamped_ts(AdmQ* A);

// parse one queued record into the WireColumns scalars — semantics
// are unpack_wire_votes' exactly (value rides UNCLAMPED when the nil
// flag is clear; deeper screens stay with the batcher)
inline void parse_record(const NRec& r, int64_t k, int64_t* inst,
                         int64_t* val, int64_t* hts, int64_t* rnd,
                         int64_t* typ, int64_t* value, uint8_t* sigs,
                         uint8_t* ver, uint8_t* out_dig, double* ts) {
  const uint8_t* p = r.raw;
  uint32_t u32;
  std::memcpy(&u32, p + 0, 4);
  inst[k] = u32;
  std::memcpy(&u32, p + 4, 4);
  val[k] = u32;
  std::memcpy(&hts[k], p + 8, 8);
  int32_t i32;
  std::memcpy(&i32, p + 16, 4);
  rnd[k] = i32;
  typ[k] = p[20];
  // nil flag: ANY nonzero byte is non-nil (unpack_wire_votes'
  // `rec[:, 21] != 0` — not bit0; a hostile flag byte of 2 must
  // drain identically on both implementations)
  if (p[21])
    std::memcpy(&value[k], p + 24, 8);
  else
    value[k] = -1;
  std::memcpy(sigs + 64 * k, p + 32, 64);
  ver[k] = r.verified;
  if (out_dig) std::memcpy(out_dig + 32 * k, r.digest, 32);
  ts[k] = r.ts;
}

// Zero-copy densify over popped rows (admission_phases.cpp): fills the
// per-phase slot/mask planes and the padded SignedLanes arrays that
// VoteBatcher.build_phases_device would have produced, IFF the rows
// are device-verify eligible by the batcher's exact rules; bails
// (returns 0) to the Python path otherwise.  Plain columns must
// already be parsed (parse_record) — densify reads them, it never
// re-reads raw bytes except signatures/pubkeys for the lane blocks.
struct PhaseIn {
  const int64_t* heights;     // [I] batcher window heights
  const int64_t* base_round;  // [I]
  int64_t W;                  // window rounds
  const int64_t* slot_lut;    // [I*S] dense SlotMap export, -1 empty
  int64_t S;                  // slots per instance
  int64_t V;                  // validators
  const uint8_t* pubkeys;     // [V*32]
  int64_t I;
  int64_t lane_floor;         // ladder.min_rung
  int64_t max_votes;          // ladder.max_rung (defer threshold)
  int64_t phase_offset;
  int64_t pad_cap;            // allocated lane rows
};

struct PhaseOut {
  int32_t* slots;       // [2*I*V], plane-major; used planes filled
  uint8_t* mask;        // [2*I*V]
  int64_t* ph_typ;      // [2]
  int64_t* ph_counts;   // [2]
  int32_t* ln_pub;      // [pad_cap*32]
  int32_t* ln_sig;      // [pad_cap*64]
  uint32_t* ln_blocks;  // [pad_cap*32] big-endian SHA-512 words
  int32_t* ln_phase_idx;  // [pad_cap]
  int32_t* ln_inst;     // [pad_cap]
  int32_t* ln_val;      // [pad_cap]
  uint8_t* ln_real;     // [pad_cap]
  int64_t* ln_rows;     // [n] lane -> drained-row permutation (the
                        //     Python build's phase-grouped cat order;
                        //     the adopter's last_build_keys gather)
  int64_t* meta;        // [status, n_phases, n_lanes, n_pad, round]
};

int densify_phases(const std::vector<NRec>& rows, const int64_t* inst,
                   const int64_t* val, const int64_t* hts,
                   const int64_t* rnd, const int64_t* typ,
                   const int64_t* value, const uint8_t* ver,
                   const PhaseIn& in, const PhaseOut& out);

}  // namespace agnes_adm
