"""ctypes bindings over the native C++ core (core/native/).

The Python-facing API intentionally mirrors `core.state_machine` /
`core.round_votes` so tests can differentially drive both
implementations with the same inputs:

  native_apply(state, round, event) -> (state', Message | None)
      takes/returns the *Python* State/Event/Message types.
  NativeRoundVotes              mirrors core.round_votes.RoundVotes.
  NativeValidatorSet            mirrors core.validators.ValidatorSet
                                (sorted/deduped, proposer rotation).
  pubkey/sign/verify/verify_batch   host Ed25519 (C++).

This is the host-parity runtime path (SURVEY.md §7 "core/"): fast
native code for the driver's per-message work, with the batched JAX
plane handling the bulk verify/tally.
"""

from __future__ import annotations

import ctypes
from typing import List, Optional, Sequence, Tuple

from agnes_tpu.core import state_machine as sm
from agnes_tpu.core.round_votes import Equivocation, Thresh, ThreshKind
from agnes_tpu.core.native_build import lib
from agnes_tpu.types import Vote, VoteType

_NO = -1


class _AgState(ctypes.Structure):
    _fields_ = [("height", ctypes.c_int64), ("round", ctypes.c_int64),
                ("step", ctypes.c_int32), ("has_locked", ctypes.c_int32),
                ("has_valid", ctypes.c_int32),
                ("locked_round", ctypes.c_int64),
                ("locked_value", ctypes.c_int64),
                ("valid_round", ctypes.c_int64),
                ("valid_value", ctypes.c_int64)]


class _AgEvent(ctypes.Structure):
    _fields_ = [("tag", ctypes.c_int32), ("has_value", ctypes.c_int32),
                ("value", ctypes.c_int64), ("pol_round", ctypes.c_int64)]


class _AgMessage(ctypes.Structure):
    _fields_ = [("tag", ctypes.c_int32), ("round", ctypes.c_int64),
                ("p_value", ctypes.c_int64), ("p_pol_round", ctypes.c_int64),
                ("v_typ", ctypes.c_int32), ("v_has_value", ctypes.c_int32),
                ("v_value", ctypes.c_int64), ("t_step", ctypes.c_int32),
                ("d_round", ctypes.c_int64), ("d_value", ctypes.c_int64)]


def _configure(L):
    L.ag_apply.argtypes = [ctypes.POINTER(_AgState), ctypes.c_int64,
                           ctypes.POINTER(_AgEvent),
                           ctypes.POINTER(_AgState),
                           ctypes.POINTER(_AgMessage)]
    L.ag_tally_new.restype = ctypes.c_void_p
    L.ag_tally_new.argtypes = [ctypes.c_int64] * 3
    L.ag_tally_free.argtypes = [ctypes.c_void_p]
    L.ag_tally_add.restype = ctypes.c_int32
    L.ag_tally_add.argtypes = [ctypes.c_void_p, ctypes.c_int32,
                               ctypes.c_int64, ctypes.c_int64,
                               ctypes.c_int64,
                               ctypes.POINTER(ctypes.c_int64)]
    L.ag_tally_skip_weight.restype = ctypes.c_int64
    L.ag_tally_skip_weight.argtypes = [ctypes.c_void_p]
    L.ag_tally_equiv_count.restype = ctypes.c_int64
    L.ag_tally_equiv_count.argtypes = [ctypes.c_void_p]
    L.ag_tally_equivocations.restype = ctypes.c_int64
    L.ag_tally_equivocations.argtypes = [ctypes.c_void_p,
                                         ctypes.POINTER(ctypes.c_int64),
                                         ctypes.c_int64]
    L.ag_valset_new.restype = ctypes.c_void_p
    L.ag_valset_new.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    L.ag_valset_free.argtypes = [ctypes.c_void_p]
    L.ag_valset_len.restype = ctypes.c_int64
    L.ag_valset_len.argtypes = [ctypes.c_void_p]
    L.ag_valset_total_power.restype = ctypes.c_int64
    L.ag_valset_total_power.argtypes = [ctypes.c_void_p]
    L.ag_valset_index_of.restype = ctypes.c_int64
    L.ag_valset_index_of.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    L.ag_rotation_new.restype = ctypes.c_void_p
    L.ag_rotation_new.argtypes = [ctypes.c_void_p]
    L.ag_rotation_free.argtypes = [ctypes.c_void_p]
    L.ag_rotation_step.restype = ctypes.c_int64
    L.ag_rotation_step.argtypes = [ctypes.c_void_p]
    L.ag_valset_hash.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    L.ag_valset_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    L.ag_valset_update.restype = ctypes.c_int32
    L.ag_valset_update.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_int64]
    L.ag_valset_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_int64]
    L.ag_valset_remove.restype = ctypes.c_int32
    L.ag_valset_remove.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    L.ag_sha512.argtypes = [ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p]
    L.ag_ed25519_pubkey.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    L.ag_ed25519_sign.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                  ctypes.c_int64, ctypes.c_char_p]
    L.ag_ed25519_verify.restype = ctypes.c_int32
    L.ag_ed25519_verify.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                    ctypes.c_int64, ctypes.c_char_p]
    L.ag_ed25519_verify_batch.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                          ctypes.c_char_p, ctypes.c_int64,
                                          ctypes.c_int64, ctypes.c_char_p]
    return L


_L = None


def _lib():
    global _L
    if _L is None:
        _L = _configure(lib())
    return _L


# --- state machine ----------------------------------------------------------

def _to_c_state(s: sm.State) -> _AgState:
    return _AgState(
        height=s.height, round=s.round, step=int(s.step),
        has_locked=int(s.locked is not None),
        has_valid=int(s.valid is not None),
        locked_round=s.locked.round if s.locked else _NO,
        locked_value=s.locked.value if s.locked else _NO,
        valid_round=s.valid.round if s.valid else _NO,
        valid_value=s.valid.value if s.valid else _NO)


def _from_c_state(c: _AgState) -> sm.State:
    return sm.State(
        height=c.height, round=c.round, step=sm.Step(c.step),
        locked=sm.RoundValue(c.locked_round, c.locked_value)
        if c.has_locked else None,
        valid=sm.RoundValue(c.valid_round, c.valid_value)
        if c.has_valid else None)


def _from_c_message(m: _AgMessage) -> Optional[sm.Message]:
    tag = sm.MsgTag(m.tag)
    if tag == sm.MsgTag.NONE:
        return None
    if tag == sm.MsgTag.NEW_ROUND:
        return sm.Message.new_round(m.round)
    if tag == sm.MsgTag.PROPOSAL:
        return sm.Message.proposal_msg(m.round, m.p_value, m.p_pol_round)
    if tag == sm.MsgTag.VOTE:
        value = m.v_value if m.v_has_value else None
        ctor = (sm.Message.prevote if m.v_typ == int(VoteType.PREVOTE)
                else sm.Message.precommit)
        return ctor(m.round, value)
    if tag == sm.MsgTag.TIMEOUT:
        return sm.Message.timeout_msg(m.round, sm.TimeoutStep(m.t_step))
    return sm.Message.decision_msg(m.d_round, m.d_value)


def native_apply(s: sm.State, round: int, event: sm.Event
                 ) -> Tuple[sm.State, Optional[sm.Message]]:
    """C++ `apply` with the Python core's types (differential surface)."""
    L = _lib()
    c_ev = _AgEvent(tag=int(event.tag),
                    has_value=int(event.value is not None),
                    value=event.value if event.value is not None else _NO,
                    pol_round=event.pol_round)
    c_in = _to_c_state(s)
    c_out, c_msg = _AgState(), _AgMessage()
    L.ag_apply(ctypes.byref(c_in), round, ctypes.byref(c_ev),
               ctypes.byref(c_out), ctypes.byref(c_msg))
    return _from_c_state(c_out), _from_c_message(c_msg)


# --- tally ------------------------------------------------------------------

class NativeRoundVotes:
    """C++ RoundVotes mirroring core.round_votes.RoundVotes."""

    def __init__(self, height: int, round: int, total: int):
        L = _lib()
        self._h = L.ag_tally_new(height, round, total)
        self._free = L.ag_tally_free   # bound now: module globals are
        self._height, self._round = height, round  # gone at shutdown

    def __del__(self):
        if getattr(self, "_h", None):
            self._free(self._h)
            self._h = None

    def add_vote(self, vote: Vote, weight: int) -> Thresh:
        tv = ctypes.c_int64(0)
        kind = _lib().ag_tally_add(
            self._h, int(vote.typ),
            vote.validator if vote.validator is not None else _NO,
            vote.value if vote.value is not None else _NO,
            weight, ctypes.byref(tv))
        kind = ThreshKind(kind)
        value = tv.value if kind == ThreshKind.VALUE else None
        return Thresh(kind, value)

    def skip_weight(self) -> int:
        return _lib().ag_tally_skip_weight(self._h)

    @property
    def equivocations(self) -> List[Equivocation]:
        cap = _lib().ag_tally_equiv_count(self._h)
        if cap == 0:
            return []
        buf = (ctypes.c_int64 * (5 * cap))()
        n = _lib().ag_tally_equivocations(self._h, buf, cap)
        out = []
        for i in range(n):
            r, typ, val, first, second = buf[5 * i:5 * i + 5]
            out.append(Equivocation(
                self._height, r, VoteType(typ), val,
                None if first == _NO else first,
                None if second == _NO else second))
        return out


# --- validator set ----------------------------------------------------------

class NativeValidatorSet:
    """C++ ValidatorSet: address-sorted, deduped, hashable, with
    weighted-round-robin proposer selection (validators.rs §2.6 intent +
    the executor's "check if we're the proposer" stub,
    consensus_executor.rs:31-33)."""

    def __init__(self, validators: Sequence[Tuple[bytes, int]]):
        packed = b"".join(
            pk + int(power).to_bytes(8, "little", signed=True)
            for pk, power in validators)
        L = _lib()
        self._h = L.ag_valset_new(packed, len(validators))
        self._free = L.ag_valset_free  # bound now, survives shutdown

    def __del__(self):
        if getattr(self, "_h", None):
            self._free(self._h)
            self._h = None

    def __len__(self) -> int:
        return _lib().ag_valset_len(self._h)

    @property
    def total_power(self) -> int:
        return _lib().ag_valset_total_power(self._h)

    def index_of(self, pubkey: bytes) -> int:
        return _lib().ag_valset_index_of(self._h, pubkey)

    def hash(self) -> bytes:
        out = ctypes.create_string_buffer(32)
        _lib().ag_valset_hash(self._h, out)
        return out.raw

    def validators(self) -> List[Tuple[bytes, int]]:
        n = len(self)
        out = ctypes.create_string_buffer(40 * n)
        _lib().ag_valset_get(self._h, out)
        raw = out.raw
        return [(raw[40 * i:40 * i + 32],
                 int.from_bytes(raw[40 * i + 32:40 * i + 40], "little",
                                signed=True))
                for i in range(n)]

    def add(self, pubkey: bytes, power: int) -> None:
        _lib().ag_valset_add(self._h, pubkey, power)

    def update(self, pubkey: bytes, power: int) -> bool:
        return bool(_lib().ag_valset_update(self._h, pubkey, power))

    def remove(self, pubkey: bytes) -> bool:
        return bool(_lib().ag_valset_remove(self._h, pubkey))


class NativeProposerRotation:
    """C++ ProposerRotation: the exact stateful priority algorithm of
    core.validators.ProposerRotation, so host-native, host-Python and
    the device proposer table all name the same proposer for every
    (height, round) slot.  Keeps the validator set alive (non-owning
    pointer on the C++ side)."""

    def __init__(self, vset: NativeValidatorSet):
        L = _lib()
        self._vset = vset                       # lifetime anchor
        self._h = L.ag_rotation_new(vset._h)
        self._free = L.ag_rotation_free

    def __del__(self):
        if getattr(self, "_h", None):
            self._free(self._h)
            self._h = None

    def step(self) -> int:
        return _lib().ag_rotation_step(self._h)


# --- crypto -----------------------------------------------------------------

def sha512(data: bytes) -> bytes:
    out = ctypes.create_string_buffer(64)
    _lib().ag_sha512(data, len(data), out)
    return out.raw


def pubkey(seed: bytes) -> bytes:
    if len(seed) != 32:
        raise ValueError("ed25519 seed must be 32 bytes")
    out = ctypes.create_string_buffer(32)
    _lib().ag_ed25519_pubkey(seed, out)
    return out.raw


def sign(seed: bytes, msg: bytes) -> bytes:
    if len(seed) != 32:
        raise ValueError("ed25519 seed must be 32 bytes")
    out = ctypes.create_string_buffer(64)
    _lib().ag_ed25519_sign(seed, msg, len(msg), out)
    return out.raw


def verify(pk: bytes, msg: bytes, sig: bytes) -> bool:
    # The C ABI reads pk[0..31] and sig[0..63] unconditionally; length
    # must be enforced here or attacker-length inputs become OOB reads.
    if len(pk) != 32 or len(sig) != 64:
        return False
    return bool(_lib().ag_ed25519_verify(pk, msg, len(msg), sig))


def verify_batch(pks: Sequence[bytes], msgs: Sequence[bytes],
                 sigs: Sequence[bytes]) -> List[bool]:
    """Host batch verify (fixed-length messages) — the C++ fallback and
    oracle for the JAX batch kernel."""
    if not pks:
        return []
    msg_len = len(msgs[0])
    assert all(len(m) == msg_len for m in msgs)
    ok_idx = [i for i in range(len(pks))
              if len(pks[i]) == 32 and len(sigs[i]) == 64]
    if len(ok_idx) != len(pks):
        # keep the packed C call aligned: verify well-formed entries
        # only, report False for the rest
        sub = verify_batch([pks[i] for i in ok_idx],
                           [msgs[i] for i in ok_idx],
                           [sigs[i] for i in ok_idx])
        res = [False] * len(pks)
        for i, good in zip(ok_idx, sub):
            res[i] = good
        return res
    out = ctypes.create_string_buffer(len(pks))
    _lib().ag_ed25519_verify_batch(
        b"".join(pks), b"".join(sigs), b"".join(msgs),
        msg_len, len(pks), out)
    return [b != 0 for b in out.raw]
