"""Event producer: votes in, state-machine events out.

Reference parity: src/vote_executor.rs (37 LoC).  `VoteExecutor` adds a
vote to the tally and maps the resulting (vote type, threshold) pair to a
state-machine event via the table at vote_executor.rs:26-36.  There is
still no "PrecommitNil" event, but one cell deviates deliberately: a
precommit-NIL quorum maps to PRECOMMIT_ANY (the reference maps it to no
event at all, which starves the spec line 47 timeout and stalls the
round on a pure-nil precommit quorum — see :func:`to_event`).

Two reference TODOs completed here (SURVEY.md §2.4):

* **Multi-round.**  The reference tracks round 0 only (vote_executor.rs:9,
  :14 "TODO more rounds").  `HeightVotes` keeps a `RoundVotes` per round,
  created on first vote for that round — this is also the `HeightVotes {}`
  placeholder of consensus_executor.rs:5 made real.

* **Edge-triggered events.**  The reference re-emits the threshold event on
  every vote after a quorum is crossed (recomputed each add,
  vote_executor.rs:20-23); at 10k-instance scale re-firing is wasted work
  (SURVEY.md §2.4).  With ``edge_triggered=True`` an event fires only on
  the add that first crosses its threshold.  Edge-triggering alone would
  be a liveness bug, though: a threshold that fires while the state
  machine is in a step that ignores it (e.g. POLKA_VALUE arriving before
  the delayed proposal, state still at Propose) would be consumed and
  never re-fire.  The reference's level-triggered re-fire masks this; an
  edge-triggered consumer MUST call :meth:`threshold_events` to re-query
  reached thresholds whenever the machine's (round, step) changes — the
  ConsensusExecutor does exactly that.  The default is ``False``
  (reference semantics, safe for naive consumers).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from agnes_tpu.core import state_machine as sm
from agnes_tpu.core.round_votes import (
    Equivocation,
    RoundVotes,
    Thresh,
    ThreshKind,
    is_one_third,
)
from agnes_tpu.types import Vote, VoteType


def to_event(typ: VoteType, thresh: Thresh) -> Optional[sm.Event]:
    """Map a (vote type, threshold) pair to a state-machine event
    (reference: vote_executor.rs:26-36).

    One deliberate deviation: the reference maps (Precommit, Nil) to no
    event (vote_executor.rs:33, "spec handles +2/3 precommit-nil via
    TimeoutPrecommit").  But TimeoutPrecommit is only ever *scheduled* by
    PrecommitAny (spec line 47, which counts precommits "for *" — nil
    included), and the tally's Nil-over-Any priority
    (round_votes.rs:58-66) shadows Any whenever the quorum is pure nil —
    so in the reference a pure-nil precommit quorum produces no event at
    all and the round stalls.  Here (Precommit, Nil) maps to
    PRECOMMIT_ANY: still no "PrecommitNil" event (parity), and the spec's
    timeout path actually triggers."""
    if thresh.kind == ThreshKind.INIT:
        return None
    if typ == VoteType.PREVOTE:
        if thresh.kind == ThreshKind.ANY:
            return sm.Event.polka_any()
        if thresh.kind == ThreshKind.NIL:
            return sm.Event.polka_nil()
        return sm.Event.polka_value(thresh.value)
    # precommits
    if thresh.kind in (ThreshKind.ANY, ThreshKind.NIL):
        return sm.Event.precommit_any()
    return sm.Event.precommit_value(thresh.value)


@dataclass
class HeightVotes:
    """Per-round tallies for one height — the realization of the
    `HeightVotes {}` placeholder (consensus_executor.rs:5)."""

    height: int
    total: int
    rounds: Dict[int, RoundVotes] = field(default_factory=dict)

    def round(self, r: int) -> RoundVotes:
        rv = self.rounds.get(r)
        if rv is None:
            rv = self.rounds[r] = RoundVotes(self.height, r, self.total)
        return rv

    def equivocations(self) -> List[Equivocation]:
        out: List[Equivocation] = []
        for rv in self.rounds.values():
            out.extend(rv.equivocations)
        return out

    def clone(self) -> "HeightVotes":
        """Per-round deep-enough copy (state-space branching)."""
        return HeightVotes(self.height, self.total,
                           {r: rv.clone() for r, rv in self.rounds.items()})


@dataclass
class VoteExecutor:
    """Adds votes, produces events (reference: vote_executor.rs:6-23)."""

    height: int
    total_weight: int
    edge_triggered: bool = False
    votes: HeightVotes = None  # type: ignore[assignment]
    # (round, produced-event tag, value) already emitted — edge-trigger
    # record.  Keyed on the EVENT, not the threshold kind: ANY and NIL
    # precommit thresholds both produce PRECOMMIT_ANY, which must fire at
    # most once per round (spec line 47 "for the first time").
    _emitted: Set[Tuple[int, sm.EventTag, Optional[int]]] = field(
        default_factory=set)
    # rounds for which RoundSkip was already emitted
    _skipped: Set[int] = field(default_factory=set)

    def __post_init__(self):
        if self.votes is None:
            self.votes = HeightVotes(self.height, self.total_weight)

    def clone(self) -> "VoteExecutor":
        """State-space branching copy; edge-trigger records included."""
        return VoteExecutor(self.height, self.total_weight,
                            self.edge_triggered, self.votes.clone(),
                            set(self._emitted), set(self._skipped))

    def apply(self, vote: Vote, weight: int) -> Optional[sm.Event]:
        """Add the vote to its round's tally; return the event its class's
        threshold maps to, if any (reference: vote_executor.rs:20-23).

        Votes stamped with a different height are ignored — the reference
        has no height on votes at all (lib.rs:23-27); here a cross-height
        vote must not count toward this height's quorums."""
        if vote.height is not None and vote.height != self.height:
            return None
        thresh = self.votes.round(vote.round).add_vote(vote, weight)
        event = to_event(vote.typ, thresh)
        if event is None or not self.edge_triggered:
            return event
        key = (vote.round, event.tag, event.value)
        if key in self._emitted:
            return None
        self._emitted.add(key)
        return event

    def threshold_events(self, round: int) -> List[sm.Event]:
        """Events for every threshold *currently* reached in `round` —
        the re-query path an edge-triggered consumer must call after the
        state machine changes (round, step), so a threshold consumed in a
        step that ignored it is not lost (see module docstring)."""
        rv = self.votes.rounds.get(round)
        if rv is None:
            return []
        events = []
        for typ, count in ((VoteType.PREVOTE, rv.prevotes),
                           (VoteType.PRECOMMIT, rv.precommits)):
            ev = to_event(typ, count.thresh())
            if ev is not None:
                events.append(ev)
        return events

    def check_round_skip(self, current_round: int) -> Optional[int]:
        """Return the lowest round r > current_round that has accumulated
        more than 1/3 of total weight, if any — the RoundSkip trigger
        (state_machine.rs:106/210; detection absent in the reference).
        Each qualifying round fires at most once."""
        for r in sorted(self.votes.rounds):
            if r <= current_round or r in self._skipped:
                continue
            if is_one_third(self.votes.round(r).skip_weight(), self.total_weight):
                self._skipped.add(r)
                return r
        return None
