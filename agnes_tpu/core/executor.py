"""The consensus executor: the completed top-level driver.

The reference's `ConsensusExecutor` is a skeleton whose every output
reaction is a stub (consensus_executor.rs:24-49: "check if we're the
proposer" :31-33, "sign the proposal; call execute" :34-37, "sign the
vote; call execute" :38-41, "schedule the timeout" :42-44, "update the
state" :45-47), with empty `HeightVotes {}`/`ValidatorSet {}`
placeholders (:5-6) and weight hardcoded to 1 (:62-63).  This module
fills every stub:

  * proposer selection — the shared `ProposerRotation` sequence;
  * signing — Ed25519 over the canonical encodings (crypto.encoding),
    C++-native when available, oracle otherwise;
  * signature verification + real voting-power weights on inbound
    votes (consensus_executor.rs:57 "TODO check validity", :62-63);
  * timeout scheduling — a virtual-time `TimerWheel` with the classic
    round-escalating durations (the consumer owns the clock, reference
    README.md:46-49: the driver advances time and feeds expirations
    back in);
  * re-entrant execution — self-produced proposals/votes loop back
    through `execute` exactly like peer messages (the intent of the
    "call execute" comments, :36, :40);
  * decision handling + height advance (README.md:43-44: a decision
    ends the instance; the driver starts the next height);
  * multi-height bookkeeping — one `VoteExecutor` (real `HeightVotes`)
    per height, late votes for decided heights dropped.

The executor is deliberately sans-I/O: outbound wire messages land in
`outbox` (the network consumer drains it), timers in the wheel.  That
keeps the reference's testability argument intact (README.md:8-14) —
the harness drives N executors with a toy router and no real network.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from agnes_tpu.core import state_machine as sm
from agnes_tpu.core.validators import ProposerRotation, ValidatorSet
from agnes_tpu.core.vote_executor import VoteExecutor
from agnes_tpu.crypto import encoding
from agnes_tpu.types import MAX_ROUND, Proposal, Vote

from agnes_tpu.crypto import host_sign as _sign, host_verify as _verify


# wire field bounds: value ids are 31-bit (types.py), rounds live in
# the shared framework domain [-1, types.MAX_ROUND] every plane
# saturates at — the screen and the saturation MUST move together
_MAX_VALUE = 2**31 - 1
_MAX_ROUND = MAX_ROUND


def _valid_value(v: Optional[int]) -> bool:
    return v is None or 0 <= v <= _MAX_VALUE


def _valid_round(r: int, allow_neg1: bool = False) -> bool:
    lo = -1 if allow_neg1 else 0
    return lo <= r <= _MAX_ROUND


# --- wire messages (the executor's inbound alphabet,
# consensus_executor.rs:16-20, plus the identity/signature surface) ---------


@dataclass(frozen=True, slots=True)
class WireProposal:
    height: int
    round: int
    value: int
    pol_round: int
    proposer: int                      # validator index
    signature: Optional[bytes] = None


@dataclass(frozen=True, slots=True)
class WireTimeout:
    height: int
    round: int
    step: sm.TimeoutStep


WireMessage = object  # WireProposal | Vote | WireTimeout


# --- timer wheel ------------------------------------------------------------


@dataclass(order=True)
class _TimerEntry:
    deadline: float
    seq: int
    timeout: WireTimeout = field(compare=False)


class TimerWheel:
    """Virtual-time timeout scheduler.  The driver advances `now` and
    feeds expired timeouts back into the executor — timeouts are just
    injected events, exactly the reference's testing philosophy
    (state_machine.rs:107-109, SURVEY.md §4)."""

    def __init__(self):
        self._heap: List[_TimerEntry] = []
        self._seq = 0
        self.now = 0.0

    def schedule(self, at: float, timeout: WireTimeout) -> None:
        heapq.heappush(self._heap, _TimerEntry(at, self._seq, timeout))
        self._seq += 1

    def advance(self, to: float) -> List[WireTimeout]:
        """Move the clock forward; pop every timeout due by `to`."""
        self.now = max(self.now, to)
        due = []
        while self._heap and self._heap[0].deadline <= self.now:
            due.append(heapq.heappop(self._heap).timeout)
        return due

    def next_deadline(self) -> Optional[float]:
        return self._heap[0].deadline if self._heap else None


@dataclass(frozen=True)
class TimeoutConfig:
    """Round-escalating timeout durations (virtual units): the classic
    Tendermint schedule base + delta * round."""

    propose: float = 3.0
    prevote: float = 1.0
    precommit: float = 1.0
    delta: float = 0.5

    def duration(self, step: sm.TimeoutStep, round: int) -> float:
        base = {sm.TimeoutStep.PROPOSE: self.propose,
                sm.TimeoutStep.PREVOTE: self.prevote,
                sm.TimeoutStep.PRECOMMIT: self.precommit}[step]
        return base + self.delta * round


# --- the executor -----------------------------------------------------------

# proposer schedule window: rounds >= this reuse the slot modulo the
# window (the rotation sequence needs a bounded (height, round) grid)
ROUNDS_WINDOW = 16


@dataclass
class Decision:
    height: int
    round: int
    value: int


class ConsensusExecutor:
    """One node's host driver (the completed consensus_executor.rs).

    Parameters
    ----------
    vset : the validator set (shared by all nodes).
    index : this node's validator index in the (sorted) set, or None
        for an observer that only follows.
    seed : Ed25519 seed for signing own messages (required with index).
    get_value : height -> value id to propose (the mempool stand-in;
        reference leaves value sourcing to the consumer).
    is_valid : value id -> bool (proposal validity, the :57 TODO).
    """

    def __init__(self, vset: ValidatorSet, index: Optional[int],
                 seed: Optional[bytes],
                 get_value: Callable[[int], int],
                 is_valid: Callable[[int], bool] = lambda v: True,
                 timeout_config: TimeoutConfig = TimeoutConfig(),
                 start_height: int = 0,
                 verify_signatures: bool = True):
        self.vset = vset
        self.index = index
        self.seed = seed
        self.get_value = get_value
        self.is_valid = is_valid
        self.tcfg = timeout_config
        self.verify_signatures = verify_signatures

        self.height = start_height
        self.state = sm.State.new(start_height)
        self.votes = VoteExecutor(height=start_height,
                                  total_weight=vset.total_power,
                                  edge_triggered=True)
        self.wheel = TimerWheel()
        self.outbox: List[WireMessage] = []
        self.decisions: List[Decision] = []
        self.decided: Dict[int, Decision] = {}
        # slashing evidence archived across heights (the per-height
        # VoteExecutor is replaced on decision; evidence must survive)
        self.evidence: List[object] = []

        self._rotation = ProposerRotation(vset)
        self._proposer_cache: Dict[Tuple[int, int], int] = {}
        self._rotation_pos = (start_height, 0)
        self._started = False

    # -- proposer schedule --------------------------------------------------

    def proposer(self, height: int, round: int) -> int:
        """Proposer index for (height, round): the global rotation
        sequence walked in (height, round % window) lexicographic
        order, cached; identical across all nodes and the device
        proposer table."""
        key = (height, round % ROUNDS_WINDOW)
        while key not in self._proposer_cache:
            h, r = self._rotation_pos
            self._proposer_cache[(h, r)] = self._rotation.step()
            self._rotation_pos = (h, r + 1) if r + 1 < ROUNDS_WINDOW \
                else (h + 1, 0)
        return self._proposer_cache[key]

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        """Enter the current height's round 0 (the consumer kick-off the
        reference leaves implicit)."""
        if self._started:
            return
        self._started = True
        self._enter_round(0)

    def _enter_round(self, round: int) -> None:
        """Feed the NewRound/NewRoundProposer event for `round`
        (consensus_executor.rs:31-33 made real)."""
        if self.index is not None and \
                self.proposer(self.height, round) == self.index:
            ev = sm.Event.new_round_proposer(self.get_value(self.height))
        else:
            ev = sm.Event.new_round()
        self._apply_event(round, ev)

    # -- inbound ------------------------------------------------------------

    def execute(self, msg: WireMessage) -> None:
        """Process one wire message (consensus_executor.rs:24-49)."""
        if not self._started:
            self.start()
        if isinstance(msg, WireProposal):
            self._on_proposal(msg)
        elif isinstance(msg, Vote):
            self._on_vote(msg)
        elif isinstance(msg, WireTimeout):
            self._on_timeout(msg)
        else:
            raise TypeError(f"unknown wire message {msg!r}")

    def _on_proposal(self, p: WireProposal) -> None:
        if p.height != self.height:
            return
        if not (_valid_round(p.round) and _valid_value(p.value)
                and _valid_round(p.pol_round, allow_neg1=True)
                and 0 <= p.proposer < len(self.vset)):
            return  # malformed fields must not crash or tally
        ok = True
        if self.verify_signatures:
            expected = self.proposer(p.height, p.round)
            ok = (p.proposer == expected and p.signature is not None
                  and _verify(
                      self.vset[p.proposer].public_key,
                      encoding.proposal_signing_bytes(
                          p.height, p.round, p.pol_round, p.value),
                      p.signature))
        if ok and self.is_valid(p.value):
            self._apply_event(p.round, sm.Event.proposal(p.pol_round,
                                                         p.value))
        else:
            self._apply_event(p.round, sm.Event.proposal_invalid())

    def _on_vote(self, v: Vote) -> None:
        if v.height is not None and v.height != self.height:
            return
        # field sanity before anything touches signing-byte encoders:
        # Byzantine peers must not be able to crash the node with
        # out-of-range integers (value ids are 31-bit, types.py)
        if not _valid_round(v.round) or not _valid_value(v.value):
            return
        weight = 1
        if v.validator is not None:
            if not (0 <= v.validator < len(self.vset)):
                return
            if self.verify_signatures:
                if v.signature is None or not _verify(
                        self.vset[v.validator].public_key,
                        encoding.vote_signing_bytes(
                            self.height, v.round, int(v.typ), v.value),
                        v.signature):
                    return  # forged or unsigned: never reaches the tally
            weight = self.vset[v.validator].voting_power
        elif self.verify_signatures:
            # identity-free votes are a test-only surface (reference
            # parity in the pure core); a verifying executor must drop
            # them — weight-1 anonymous votes would forge quorums
            return
        event = self.votes.apply(v, weight)
        if event is not None:
            self._apply_event(v.round, event)
        skip = self.votes.check_round_skip(self.state.round)
        if skip is not None:
            self._apply_event(skip, sm.Event.round_skip())

    def _on_timeout(self, t: WireTimeout) -> None:
        if t.height != self.height:
            return
        ev = {sm.TimeoutStep.PROPOSE: sm.Event.timeout_propose,
              sm.TimeoutStep.PREVOTE: sm.Event.timeout_prevote,
              sm.TimeoutStep.PRECOMMIT: sm.Event.timeout_precommit}[t.step]()
        self._apply_event(t.round, ev)

    # -- core loop ----------------------------------------------------------

    def _apply_event(self, round: int, event: sm.Event) -> None:
        before = (self.state.round, self.state.step)
        self.state, msg = self.state.apply(round, event)
        if msg is not None:
            self._react(msg)
        after = (self.state.round, self.state.step)
        if after != before and self.state.step != sm.Step.COMMIT:
            self._requery(after)

    def _requery(self, pos: Tuple[int, int]) -> None:
        """Re-deliver thresholds already reached that the new (round,
        step) can now consume — the edge-trigger liveness companion
        (vote_executor.py module docstring)."""
        round = pos[0]
        for ev in self.votes.threshold_events(round):
            self._apply_event(round, ev)

    def _react(self, msg: sm.Message) -> None:
        """The five reactions, un-stubbed (consensus_executor.rs:30-48)."""
        tag = msg.tag
        if tag == sm.MsgTag.NEW_ROUND:
            self._enter_round(msg.round)
        elif tag == sm.MsgTag.PROPOSAL:
            self._broadcast_proposal(msg.proposal)
        elif tag == sm.MsgTag.VOTE:
            self._broadcast_vote(msg.vote)
        elif tag == sm.MsgTag.TIMEOUT:
            t = WireTimeout(self.height, msg.timeout.round,
                            msg.timeout.step)
            self.wheel.schedule(
                self.wheel.now + self.tcfg.duration(msg.timeout.step,
                                                    msg.timeout.round), t)
        elif tag == sm.MsgTag.DECISION:
            self._decide(msg.decision)

    def _broadcast_proposal(self, p: Proposal) -> None:
        sig = None
        if self.seed is not None:
            sig = _sign(self.seed, encoding.proposal_signing_bytes(
                self.height, p.round, p.pol_round, p.value))
        wire = WireProposal(self.height, p.round, p.value, p.pol_round,
                            self.index, sig)
        self.outbox.append(wire)
        self.execute(wire)          # re-entrant self-delivery (:36)

    def _broadcast_vote(self, v: Vote) -> None:
        sig = None
        if self.seed is not None:
            sig = _sign(self.seed, encoding.vote_signing_bytes(
                self.height, v.round, int(v.typ), v.value))
        wire = Vote(typ=v.typ, round=v.round, value=v.value,
                    validator=self.index, height=self.height, signature=sig)
        self.outbox.append(wire)
        self.execute(wire)          # re-entrant self-delivery (:40)

    def _decide(self, d: sm.RoundValue) -> None:
        """Record the decision and advance to the next height
        (README.md:43-44)."""
        dec = Decision(self.height, d.round, d.value)
        self.decisions.append(dec)
        self.decided[self.height] = dec
        # dedup: a restart restores live-height evidence into the archive,
        # and peers redelivering the same votes would re-detect it here
        seen = set(self.evidence)
        self.evidence.extend(e for e in self.votes.votes.equivocations()
                             if e not in seen)
        self.height += 1
        self.state = sm.State.new(self.height)
        self.votes = VoteExecutor(height=self.height,
                                  total_weight=self.vset.total_power,
                                  edge_triggered=True)
        self._enter_round(0)

    # -- evidence ------------------------------------------------------------

    def all_equivocations(self) -> List[object]:
        """Archived evidence from decided heights plus the live height's
        (deduplicated — after a restart the archive already holds the
        restored live records)."""
        seen = set(self.evidence)
        return self.evidence + [e for e in self.votes.votes.equivocations()
                                if e not in seen]

    # -- timers -------------------------------------------------------------

    def advance_time(self, to: float) -> None:
        """Drive the clock; expired timeouts re-enter via execute."""
        for t in self.wheel.advance(to):
            self.execute(t)
