"""The consensus executor: the completed top-level driver.

The reference's `ConsensusExecutor` is a skeleton whose every output
reaction is a stub (consensus_executor.rs:24-49: "check if we're the
proposer" :31-33, "sign the proposal; call execute" :34-37, "sign the
vote; call execute" :38-41, "schedule the timeout" :42-44, "update the
state" :45-47), with empty `HeightVotes {}`/`ValidatorSet {}`
placeholders (:5-6) and weight hardcoded to 1 (:62-63).  This module
fills every stub:

  * proposer selection — the shared `ProposerRotation` sequence;
  * signing — Ed25519 over the canonical encodings (crypto.encoding),
    C++-native when available, oracle otherwise;
  * signature verification + real voting-power weights on inbound
    votes (consensus_executor.rs:57 "TODO check validity", :62-63);
  * timeout scheduling — a virtual-time `TimerWheel` with the classic
    round-escalating durations (the consumer owns the clock, reference
    README.md:46-49: the driver advances time and feeds expirations
    back in);
  * re-entrant execution — self-produced proposals/votes loop back
    through `execute` exactly like peer messages (the intent of the
    "call execute" comments, :36, :40);
  * decision handling + height advance (README.md:43-44: a decision
    ends the instance; the driver starts the next height);
  * multi-height bookkeeping — one `VoteExecutor` (real `HeightVotes`)
    per height, late votes for decided heights dropped.

The executor is deliberately sans-I/O: outbound wire messages land in
`outbox` (the network consumer drains it), timers in the wheel.  That
keeps the reference's testability argument intact (README.md:8-14) —
the harness drives N executors with a toy router and no real network.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from agnes_tpu.core import state_machine as sm
from agnes_tpu.core.validators import ProposerRotation, ValidatorSet
from agnes_tpu.core.vote_executor import VoteExecutor
from agnes_tpu.crypto import encoding
from agnes_tpu.types import MAX_ROUND, Proposal, Vote

from agnes_tpu.crypto import host_sign as _sign, host_verify as _verify


# wire field bounds: value ids are 31-bit (types.py), rounds live in
# the shared framework domain [-1, types.MAX_ROUND] every plane
# saturates at — the screen and the saturation MUST move together
_MAX_VALUE = 2**31 - 1
_MAX_ROUND = MAX_ROUND


def _valid_value(v: Optional[int]) -> bool:
    return v is None or 0 <= v <= _MAX_VALUE


def _valid_round(r: int, allow_neg1: bool = False) -> bool:
    lo = -1 if allow_neg1 else 0
    return lo <= r <= _MAX_ROUND


def epoch_boundary_at(epochs, height: int) -> Optional[int]:
    """Largest epoch boundary <= `height`, or None when the genesis
    set applies — THE boundary rule every plane shares (executor
    tallies, the checker's config-derived monitors, the device
    replay's set_validators install path).  `epochs` is any
    height-keyed mapping (or None/empty)."""
    best = None
    for boundary in epochs or ():
        if boundary <= height and (best is None or boundary > best):
            best = boundary
    return best


# --- wire messages (the executor's inbound alphabet,
# consensus_executor.rs:16-20, plus the identity/signature surface) ---------


@dataclass(frozen=True, slots=True)
class WireProposal:
    height: int
    round: int
    value: int
    pol_round: int
    proposer: int                      # validator index
    signature: Optional[bytes] = None


@dataclass(frozen=True, slots=True)
class WireTimeout:
    height: int
    round: int
    step: sm.TimeoutStep


WireMessage = object  # WireProposal | Vote | WireTimeout


# --- timer wheel ------------------------------------------------------------


@dataclass(order=True)
class _TimerEntry:
    deadline: float
    seq: int
    timeout: WireTimeout = field(compare=False)


class TimerWheel:
    """Virtual-time timeout scheduler.  The driver advances `now` and
    feeds expired timeouts back into the executor — timeouts are just
    injected events, exactly the reference's testing philosophy
    (state_machine.rs:107-109, SURVEY.md §4)."""

    def __init__(self):
        self._heap: List[_TimerEntry] = []
        self._seq = 0
        self.now = 0.0

    def schedule(self, at: float, timeout: WireTimeout) -> None:
        heapq.heappush(self._heap, _TimerEntry(at, self._seq, timeout))
        self._seq += 1

    def advance(self, to: float) -> List[WireTimeout]:
        """Move the clock forward; pop every timeout due by `to`."""
        self.now = max(self.now, to)
        due = []
        while self._heap and self._heap[0].deadline <= self.now:
            due.append(heapq.heappop(self._heap).timeout)
        return due

    def next_deadline(self) -> Optional[float]:
        return self._heap[0].deadline if self._heap else None

    # -- single-step scheduler surface (model checker, harness.simulator
    # step mode): under the asynchronous abstraction a scheduled timer
    # may fire at ANY point, so deadlines stop mattering and the wheel
    # becomes a pending-timeout multiset an external scheduler pops.

    def pending(self) -> List[WireTimeout]:
        """Every scheduled-but-unfired timeout (deadline-order-free)."""
        return [e.timeout for e in self._heap]

    def remove(self, timeout: WireTimeout) -> bool:
        """Remove ONE pending entry equal to `timeout` (the scheduler
        is about to fire it by hand); False if none pending.  Rebuilds
        the heap list rather than mutating it in place, so clones that
        still share the list (see `clone`) are unaffected."""
        for k, e in enumerate(self._heap):
            if e.timeout == timeout:
                rest = self._heap[:k] + self._heap[k + 1:]
                heapq.heapify(rest)
                self._heap = rest
                return True
        return False

    def clone(self) -> "TimerWheel":
        """O(pending) copy for state-space branching: entries are never
        mutated after push, so a shallow list copy suffices (`remove`
        replaces the list, `schedule` pushes onto the clone's own)."""
        w = TimerWheel.__new__(TimerWheel)
        w._heap = list(self._heap)
        w._seq = self._seq
        w.now = self.now
        return w


@dataclass(frozen=True)
class TimeoutConfig:
    """Round-escalating timeout durations (virtual units): the classic
    Tendermint schedule base + delta * round."""

    propose: float = 3.0
    prevote: float = 1.0
    precommit: float = 1.0
    delta: float = 0.5

    def duration(self, step: sm.TimeoutStep, round: int) -> float:
        base = {sm.TimeoutStep.PROPOSE: self.propose,
                sm.TimeoutStep.PREVOTE: self.prevote,
                sm.TimeoutStep.PRECOMMIT: self.precommit}[step]
        return base + self.delta * round


# --- the executor -----------------------------------------------------------

# proposer schedule window: rounds >= this reuse the slot modulo the
# window (the rotation sequence needs a bounded (height, round) grid)
ROUNDS_WINDOW = 16


@dataclass
class Decision:
    height: int
    round: int
    value: int


@dataclass(frozen=True, slots=True)
class DecisionCert:
    """The quorum a decision rested on, captured AT decide time.

    `_decide` discards the live `VoteExecutor` (the tally dies with the
    height), so anything that wants to audit "no decision without +2/3
    precommit weight" after the fact — the model checker's quorum
    monitor (analysis/modelcheck.py) — must read the weight before it
    is gone.  `weight` is the precommit weight this node had counted
    for (round, value) at the instant it decided; `total` the set's
    total power.  A legitimate decision satisfies 3*weight > 2*total.
    """

    height: int
    round: int
    value: int
    weight: int
    total: int


class ConsensusExecutor:
    """One node's host driver (the completed consensus_executor.rs).

    Parameters
    ----------
    vset : the validator set (shared by all nodes).
    index : this node's validator index in the (sorted) set, or None
        for an observer that only follows.
    seed : Ed25519 seed for signing own messages (required with index).
    get_value : height -> value id to propose (the mempool stand-in;
        reference leaves value sourcing to the consumer).
    is_valid : value id -> bool (proposal validity, the :57 TODO).
    epochs : optional validator-set epoch schedule — {boundary_height:
        (power, ...)} in set (sorted) index order.  At every height h
        the tally weights/totals come from the epoch with the largest
        boundary <= h (the vset's genesis powers below the first
        boundary) — the host-plane mirror of the device plane's
        ``set_validators`` height-boundary contract
        (harness/device_driver.py).  Identities (pubkeys, and hence
        the proposer rotation) are epoch-invariant here: a power of 0
        models removal, exactly like the device's static [V] table.
    """

    def __init__(self, vset: ValidatorSet, index: Optional[int],
                 seed: Optional[bytes],
                 get_value: Callable[[int], int],
                 is_valid: Callable[[int], bool] = lambda v: True,
                 timeout_config: TimeoutConfig = TimeoutConfig(),
                 start_height: int = 0,
                 verify_signatures: bool = True,
                 epochs: Optional[Dict[int, Tuple[int, ...]]] = None):
        self.vset = vset
        self.index = index
        self.seed = seed
        self.get_value = get_value
        self.is_valid = is_valid
        self.tcfg = timeout_config
        self.verify_signatures = verify_signatures
        self.epochs = epochs

        self.height = start_height
        self.state = sm.State.new(start_height)
        self.votes = self._new_votes(start_height)
        self.wheel = TimerWheel()
        self.outbox: List[WireMessage] = []
        self.decisions: List[Decision] = []
        self.decided: Dict[int, Decision] = {}
        # slashing evidence archived across heights (the per-height
        # VoteExecutor is replaced on decision; evidence must survive)
        self.evidence: List[object] = []
        # quorum certificates, one per decision (audit surface — see
        # DecisionCert; appended by _decide, never read by the core)
        self.decision_certs: List[DecisionCert] = []

        self._rotation = ProposerRotation(vset)
        self._proposer_cache: Dict[Tuple[int, int], int] = {}
        self._rotation_pos = (start_height, 0)
        # set by prefill_proposers(): a frozen cache may be SHARED by
        # clone() (the memo is a pure function of (height, round), but
        # the rotation cursor behind it is not clone-divergence-safe)
        self._proposer_frozen = False
        self._started = False

    # -- tally construction / weighting (subclass seams) --------------------

    def epoch_powers(self, height: int) -> Optional[Tuple[int, ...]]:
        """The per-validator power vector live at `height` under the
        epoch schedule, or None when the genesis (vset) powers apply.
        Pure in (epochs, height) — the stale-epoch mutant overrides
        the lookup height to model a node that keeps tallying against
        the previous set after a boundary."""
        best = epoch_boundary_at(self.epochs, height)
        return None if best is None else self.epochs[best]

    def epoch_total(self, height: int) -> int:
        pw = self.epoch_powers(height)
        return self.vset.total_power if pw is None else sum(pw)

    def _new_votes(self, height: int) -> VoteExecutor:
        """The per-height tally, denominated in the power total of the
        validator-set epoch live at `height`.  A seam so doctored
        executors (the model checker's mutation registry,
        analysis/modelcheck.py) can install a miscounting tally
        without copying the height-advance logic."""
        return VoteExecutor(height=height,
                            total_weight=self.epoch_total(height),
                            edge_triggered=True)

    def _vote_weight(self, v: Vote) -> int:
        """Voting power an identified inbound vote counts with — from
        the epoch live at the node's CURRENT height (votes for other
        heights never reach the tally, _on_vote's height screen).  The
        weight-blind mutant overrides this (and `_new_votes`) to count
        heads instead of power — the committee-weight bug class the
        quorum-cert monitor exists to catch."""
        pw = self.epoch_powers(self.height)
        if pw is not None:
            return pw[v.validator]
        return self.vset[v.validator].voting_power

    # -- proposer schedule --------------------------------------------------

    def proposer(self, height: int, round: int) -> int:
        """Proposer index for (height, round): the global rotation
        sequence walked in (height, round % window) lexicographic
        order, cached; identical across all nodes and the device
        proposer table."""
        key = (height, round % ROUNDS_WINDOW)
        while key not in self._proposer_cache:
            assert not self._proposer_frozen, (
                f"proposer cache frozen but {key} missed — raise the "
                f"prefill_proposers height bound")
            h, r = self._rotation_pos
            self._proposer_cache[(h, r)] = self._rotation.step()
            self._rotation_pos = (h, r + 1) if r + 1 < ROUNDS_WINDOW \
                else (h + 1, 0)
        return self._proposer_cache[key]

    def prefill_proposers(self, max_height: int) -> None:
        """Materialize the proposer schedule for every (height ≤
        max_height, round-window slot) and FREEZE the cache.  After
        this the memo is read-only, so `clone()` shares it (and the
        now-inert rotation cursor) across every branch of a state-space
        exploration — a miss past the bound asserts instead of silently
        corrupting the shared cursor."""
        for h in range(self.height, max_height + 1):
            for r in range(ROUNDS_WINDOW):
                self.proposer(h, r)
        self._proposer_frozen = True

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        """Enter the current height's round 0 (the consumer kick-off the
        reference leaves implicit)."""
        if self._started:
            return
        self._started = True
        self._enter_round(0)

    def _enter_round(self, round: int) -> None:
        """Feed the NewRound/NewRoundProposer event for `round`
        (consensus_executor.rs:31-33 made real)."""
        if self.index is not None and \
                self.proposer(self.height, round) == self.index:
            ev = sm.Event.new_round_proposer(self.get_value(self.height))
        else:
            ev = sm.Event.new_round()
        self._apply_event(round, ev)

    # -- inbound ------------------------------------------------------------

    def execute(self, msg: WireMessage) -> None:
        """Process one wire message (consensus_executor.rs:24-49)."""
        if not self._started:
            self.start()
        if isinstance(msg, WireProposal):
            self._on_proposal(msg)
        elif isinstance(msg, Vote):
            self._on_vote(msg)
        elif isinstance(msg, WireTimeout):
            self._on_timeout(msg)
        else:
            raise TypeError(f"unknown wire message {msg!r}")

    def _on_proposal(self, p: WireProposal) -> None:
        if p.height != self.height:
            return
        if not (_valid_round(p.round) and _valid_value(p.value)
                and _valid_round(p.pol_round, allow_neg1=True)
                and 0 <= p.proposer < len(self.vset)):
            return  # malformed fields must not crash or tally
        ok = True
        if self.verify_signatures:
            expected = self.proposer(p.height, p.round)
            ok = (p.proposer == expected and p.signature is not None
                  and _verify(
                      self.vset[p.proposer].public_key,
                      encoding.proposal_signing_bytes(
                          p.height, p.round, p.pol_round, p.value),
                      p.signature))
        if ok and self.is_valid(p.value):
            self._apply_event(p.round, sm.Event.proposal(p.pol_round,
                                                         p.value))
        else:
            self._apply_event(p.round, sm.Event.proposal_invalid())

    def _on_vote(self, v: Vote) -> None:
        if v.height is not None and v.height != self.height:
            return
        # field sanity before anything touches signing-byte encoders:
        # Byzantine peers must not be able to crash the node with
        # out-of-range integers (value ids are 31-bit, types.py)
        if not _valid_round(v.round) or not _valid_value(v.value):
            return
        weight = 1
        if v.validator is not None:
            if not (0 <= v.validator < len(self.vset)):
                return
            if self.verify_signatures:
                if v.signature is None or not _verify(
                        self.vset[v.validator].public_key,
                        encoding.vote_signing_bytes(
                            self.height, v.round, int(v.typ), v.value),
                        v.signature):
                    return  # forged or unsigned: never reaches the tally
            weight = self._vote_weight(v)
        elif self.verify_signatures:
            # identity-free votes are a test-only surface (reference
            # parity in the pure core); a verifying executor must drop
            # them — weight-1 anonymous votes would forge quorums
            return
        event = self.votes.apply(v, weight)
        if event is not None:
            self._apply_event(v.round, event)
        skip = self.votes.check_round_skip(self.state.round)
        if skip is not None:
            self._apply_event(skip, sm.Event.round_skip())

    def _on_timeout(self, t: WireTimeout) -> None:
        if t.height != self.height:
            return
        ev = {sm.TimeoutStep.PROPOSE: sm.Event.timeout_propose,
              sm.TimeoutStep.PREVOTE: sm.Event.timeout_prevote,
              sm.TimeoutStep.PRECOMMIT: sm.Event.timeout_precommit}[t.step]()
        self._apply_event(t.round, ev)

    # -- core loop ----------------------------------------------------------

    def _apply_event(self, round: int, event: sm.Event) -> None:
        before = (self.state.round, self.state.step)
        self.state, msg = self.state.apply(round, event)
        if msg is not None:
            self._react(msg)
        after = (self.state.round, self.state.step)
        if after != before and self.state.step != sm.Step.COMMIT:
            self._requery(after)

    def _requery(self, pos: Tuple[int, int]) -> None:
        """Re-deliver thresholds already reached that the new (round,
        step) can now consume — the edge-trigger liveness companion
        (vote_executor.py module docstring)."""
        round = pos[0]
        for ev in self.votes.threshold_events(round):
            self._apply_event(round, ev)

    def _react(self, msg: sm.Message) -> None:
        """The five reactions, un-stubbed (consensus_executor.rs:30-48)."""
        tag = msg.tag
        if tag == sm.MsgTag.NEW_ROUND:
            self._enter_round(msg.round)
        elif tag == sm.MsgTag.PROPOSAL:
            self._broadcast_proposal(msg.proposal)
        elif tag == sm.MsgTag.VOTE:
            self._broadcast_vote(msg.vote)
        elif tag == sm.MsgTag.TIMEOUT:
            t = WireTimeout(self.height, msg.timeout.round,
                            msg.timeout.step)
            self.wheel.schedule(
                self.wheel.now + self.tcfg.duration(msg.timeout.step,
                                                    msg.timeout.round), t)
        elif tag == sm.MsgTag.DECISION:
            self._decide(msg.decision)

    def _broadcast_proposal(self, p: Proposal) -> None:
        sig = None
        if self.seed is not None:
            sig = _sign(self.seed, encoding.proposal_signing_bytes(
                self.height, p.round, p.pol_round, p.value))
        wire = WireProposal(self.height, p.round, p.value, p.pol_round,
                            self.index, sig)
        self.outbox.append(wire)
        self.execute(wire)          # re-entrant self-delivery (:36)

    def _broadcast_vote(self, v: Vote) -> None:
        sig = None
        if self.seed is not None:
            sig = _sign(self.seed, encoding.vote_signing_bytes(
                self.height, v.round, int(v.typ), v.value))
        wire = Vote(typ=v.typ, round=v.round, value=v.value,
                    validator=self.index, height=self.height, signature=sig)
        self.outbox.append(wire)
        self.execute(wire)          # re-entrant self-delivery (:40)

    def _decide(self, d: sm.RoundValue) -> None:
        """Record the decision and advance to the next height
        (README.md:43-44)."""
        dec = Decision(self.height, d.round, d.value)
        self.decisions.append(dec)
        self.decided[self.height] = dec
        # capture the quorum certificate BEFORE the tally is replaced
        # (DecisionCert docstring): the precommit weight counted for
        # the decided (round, value) at this instant
        rv = self.votes.votes.rounds.get(d.round)
        weight = rv.precommits.value_weight(d.value) if rv else 0
        self.decision_certs.append(DecisionCert(
            self.height, d.round, d.value, weight,
            self.epoch_total(self.height)))
        # dedup: a restart restores live-height evidence into the archive,
        # and peers redelivering the same votes would re-detect it here
        seen = set(self.evidence)
        self.evidence.extend(e for e in self.votes.votes.equivocations()
                             if e not in seen)
        self.height += 1
        self.state = sm.State.new(self.height)
        self.votes = self._new_votes(self.height)
        self._enter_round(0)

    # -- evidence ------------------------------------------------------------

    def all_equivocations(self) -> List[object]:
        """Archived evidence from decided heights plus the live height's
        (deduplicated — after a restart the archive already holds the
        restored live records)."""
        seen = set(self.evidence)
        return self.evidence + [e for e in self.votes.votes.equivocations()
                                if e not in seen]

    # -- sleepy participation (TOB-SVD churn model) --------------------------

    def on_wake(self) -> None:
        """Hook fired when the network wakes this node from a sleepy-
        churn nap (harness/simulator.py ("w", j) action).  A correct
        node does NOTHING here: its state machine position, lock, and
        tally survived the nap untouched, and the gossip layer replays
        the traffic it missed as ordinary deliveries.  The seam exists
        for the model checker's churn-blind mutant — a node that
        treats wake as a reboot (re-entering round 0, shredding its
        lock) regresses (height, round, step) and the monotonicity
        monitor catches it."""

    # -- timers -------------------------------------------------------------

    def advance_time(self, to: float) -> None:
        """Drive the clock; expired timeouts re-enter via execute."""
        for t in self.wheel.advance(to):
            self.execute(t)

    def timer_live(self, t: WireTimeout) -> bool:
        """Can this pending timeout still take effect if fired?

        Sound in one direction only: True may still be a no-op fire,
        but False is a PROOF of no-op — height/round/step are all
        monotone (height by _decide, round within a height by
        _round_skip, step within a round by next_step/commit), and
        every timeout arm in state_machine.apply carries an `eqr`
        guard plus (for propose/prevote) a step guard.  The model
        checker uses this both to prune dead fire-actions and to drop
        dead timers from the canonical state, so states differing only
        in inert wheel residue merge."""
        if t.height != self.height or t.round != self.state.round:
            return False
        step = self.state.step
        if t.step == sm.TimeoutStep.PROPOSE:
            return step <= sm.Step.PROPOSE
        if t.step == sm.TimeoutStep.PREVOTE:
            return step <= sm.Step.PREVOTE
        return step < sm.Step.COMMIT        # PRECOMMIT: step-agnostic arm

    # -- state-space surface (analysis/modelcheck.py) -----------------------

    def clone(self) -> "ConsensusExecutor":
        """O(live state) copy for state-space branching.

        Immutable/deterministic members (vset, config callables, the
        frozen State, wire messages) are shared; every mutable
        container is copied one level deep — deep enough because the
        leaves (Vote, Equivocation, Decision, DecisionCert, State) are
        all frozen or append-only.  The proposer memo is shared ONLY
        when frozen by prefill_proposers (see there).  Subclass-safe
        for method-override doctored executors (the modelcheck
        mutation registry); a subclass adding mutable attributes must
        extend this."""
        cls = type(self)
        n = cls.__new__(cls)
        n.vset = self.vset
        n.index = self.index
        n.seed = self.seed
        n.get_value = self.get_value
        n.is_valid = self.is_valid
        n.tcfg = self.tcfg
        n.verify_signatures = self.verify_signatures
        n.epochs = self.epochs
        n.height = self.height
        n.state = self.state
        n.votes = self.votes.clone()
        n.wheel = self.wheel.clone()
        n.outbox = list(self.outbox)
        n.decisions = list(self.decisions)
        n.decided = dict(self.decided)
        n.evidence = list(self.evidence)
        n.decision_certs = list(self.decision_certs)
        if self._proposer_frozen:
            n._rotation = self._rotation
            n._proposer_cache = self._proposer_cache
        else:
            # rebuild an equivalent cursor: the rotation is a pure
            # deterministic sequence and each cache entry consumed
            # exactly one step, so re-stepping a fresh rotation
            # len(cache) times lands it where the original's is
            n._rotation = ProposerRotation(self.vset)
            for _ in range(len(self._proposer_cache)):
                n._rotation.step()
            n._proposer_cache = dict(self._proposer_cache)
        n._rotation_pos = self._rotation_pos
        n._proposer_frozen = self._proposer_frozen
        n._started = self._started
        return n

    def canonical_state(self, perm: Optional[List[int]] = None) -> tuple:
        """A canonical, hashable, int-only summary of everything that
        can influence this node's FUTURE behavior — the model checker's
        dedup key.  Deliberately excluded: outbox/decisions history
        (drained/duplicated elsewhere), decision_certs (audit log,
        checked per transition), the proposer memo (pure function),
        wheel deadlines and dead timers (the asynchronous abstraction:
        any pending live timer may fire at any point, so only the SET
        of live (round, step) timers matters).  None-valued vote
        values encode as -2 (NIL_ID is -1, real ids >= 0).

        `perm` (old validator index -> new index) relabels every
        embedded validator index — the symmetry-reduction surface
        (harness/simulator.Network.mc_canonical carries the soundness
        contract).  Voting-power weights relabel for free: they live
        in value-keyed buckets, and the group construction only
        permutes equal-power validators."""
        def _v(x):
            return -2 if x is None else x

        def _p(x):
            return x if perm is None else perm[x]

        hv = self.votes.votes
        rounds = []
        for r in sorted(hv.rounds):
            rv = hv.rounds[r]
            rounds.append((
                r,
                rv.prevotes.nil,
                tuple(sorted(rv.prevotes.weights.items())),
                rv.precommits.nil,
                tuple(sorted(rv.precommits.weights.items())),
                tuple(sorted((_p(val), int(t), _v(v), w)
                             for (val, t), (v, w) in rv.seen.items())),
                tuple(sorted((int(t), w)
                             for t, w in rv._anon_weight.items())),
                tuple(sorted((_p(e.validator), int(e.typ),
                              _v(e.first_value), _v(e.second_value))
                             for e in rv.equivocations)),
            ))
        lock = (self.state.locked.round, self.state.locked.value) \
            if self.state.locked else None
        valid = (self.state.valid.round, self.state.valid.value) \
            if self.state.valid else None
        return (
            self.height,
            self.state.round,
            int(self.state.step),
            lock,
            valid,
            tuple(rounds),
            tuple(sorted((r, int(tag), _v(v))
                         for r, tag, v in self.votes._emitted)),
            tuple(sorted(self.votes._skipped)),
            tuple(sorted((h, d.round, d.value)
                         for h, d in self.decided.items())),
            tuple(sorted((e.height, e.round, int(e.typ), _p(e.validator),
                          _v(e.first_value), _v(e.second_value))
                         for e in self.evidence)),
            tuple(sorted({(t.round, int(t.step))
                          for t in self.wheel.pending()
                          if self.timer_live(t)})),
        )
